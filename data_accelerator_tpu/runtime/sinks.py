"""Output sinks: per-dataset operators fanning rows to destinations.

reference: datax-host sink/ package —
- OutputManager.scala:22-160: sink plugin registry + per-output operator
  construction from ``datax.job.output.<name>.<sink>.*`` conf, one-time
  processed-schema dump, parallel fan-out -> ``build_output_operators`` +
  ``OutputDispatcher``.
- BlobSinker.scala:30-226: JSON(.gz) files into time-partitioned folders
  (``${yyyy/MM/dd/HH}`` + quarter-hour bucket) -> ``FileSink``.
- HttpPoster.scala:16-84 -> ``HttpPostSink``; EventHubStreamPoster ->
  stubbed send hook; metric sink -> MetricLogger routing (the reference
  routes alert tables TO Metrics the same way).

Sinks receive already-materialized host rows; device->host transfer
happens once per batch in the processor, off the jitted path.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..core.config import SettingDictionary
from ..obs import tracing
from ..obs.metrics import MetricLogger
from ..constants import MetricName
from ..utils import fs

logger = logging.getLogger(__name__)


class Sink:
    kind = "base"

    def write(self, dataset: str, rows: List[dict], batch_time_ms: int) -> int:
        raise NotImplementedError


class ConsoleSink(Sink):
    kind = "console"

    def __init__(self, max_rows: int = 20, printer: Callable = print):
        self.max_rows = max_rows
        self.printer = printer

    def write(self, dataset, rows, batch_time_ms) -> int:
        for r in rows[: self.max_rows]:
            self.printer(f"[{dataset}] {json.dumps(r, default=str)}")
        return len(rows)


def partition_folder(base: str, batch_time_ms: int) -> str:
    """Time-partitioned output folder with the reference's bucket scheme:
    ``.../{yyyy/MM/dd/HH}/{quarter-bucket}`` (BlobSinker.scala:34-51)."""
    t = time.gmtime(batch_time_ms / 1000.0)
    minute_bucket = (t.tm_min // 15) * 15
    quarter = f"{t.tm_hour:02d}{minute_bucket:02d}"
    return os.path.join(
        base,
        f"{t.tm_year:04d}/{t.tm_mon:02d}/{t.tm_mday:02d}/{t.tm_hour:02d}",
        quarter,
    )


class FileSink(Sink):
    """JSON(.gz) writer into time-partitioned folders (blob sink analog).

    Writes temp + rename for atomicity (HadoopClient.scala:391-441)."""

    kind = "file"

    def __init__(self, folder: str, compression: str = "none"):
        self.folder = folder
        self.compression = compression
        self._counter = 0

    def write(self, dataset, rows, batch_time_ms) -> int:
        if not rows:
            return 0
        out_dir = partition_folder(self.folder, batch_time_ms)
        self._counter += 1
        ext = ".json.gz" if self.compression == "gzip" else ".json"
        name = f"{dataset}_{batch_time_ms}_{self._counter}{ext}"
        path = os.path.join(out_dir, name)
        payload = "\n".join(json.dumps(r, default=str) for r in rows) + "\n"
        fs.write_text(path, payload)
        return len(rows)


class HttpPostSink(Sink):
    """Per-batch POST of events (HttpPoster.scala:16-84)."""

    kind = "httppost"

    def __init__(self, endpoint: str, headers: Optional[Dict[str, str]] = None):
        self.endpoint = endpoint
        self.headers = headers or {}

    def write(self, dataset, rows, batch_time_ms) -> int:
        if not rows:
            return 0
        req = urllib.request.Request(
            self.endpoint,
            data=json.dumps(rows, default=str).encode(),
            headers={"Content-Type": "application/json", **self.headers},
        )
        try:
            urllib.request.urlopen(req, timeout=10).read()
        except Exception as e:
            logger.warning("http sink post failed for %s: %s", dataset, e)
            return 0
        return len(rows)


class ExternalFunctionSink(Sink):
    """Per-row synchronous POST to an external function endpoint.

    reference: AzureFunctionHandler.scala:14-75 — UDFs that POST to an
    Azure Function per row (:47-66). TPU-native design keeps network
    I/O out of the compiled graph, so external functions attach at the
    output boundary: route a dataset to this sink (``OUTPUT Alerts TO
    MyFn;``) and each row is sent as the function's payload. The
    function definition comes from the same conf shape the reference
    flattens (serviceEndpoint/api/code/methodType)."""

    kind = "externalfn"

    def __init__(
        self,
        endpoint: str,
        api: str = "",
        code: str = "",
        method: str = "post",
        timeout_s: float = 10.0,
    ):
        from urllib.parse import quote

        url = endpoint.rstrip("/")
        if api:
            url += "/" + api.lstrip("/")
        if code:
            # function keys carry '+'/'=' — must be percent-encoded
            url += ("&" if "?" in url else "?") + "code=" + quote(code, safe="")
        self.url = url
        self.method = method.upper()
        self.timeout_s = timeout_s

    def write(self, dataset, rows, batch_time_ms) -> int:
        sent = 0
        for r in rows:
            req = urllib.request.Request(
                self.url,
                data=json.dumps(r, default=str).encode(),
                headers={"Content-Type": "application/json"},
                method=self.method,
            )
            try:
                urllib.request.urlopen(req, timeout=self.timeout_s).read()
                sent += 1
            except Exception as e:  # noqa: BLE001 — per-row best effort
                logger.warning(
                    "external function call failed for %s: %s", dataset, e
                )
        return sent


class SqlSink(Sink):
    """Relational sink: per-batch inserts with append/overwrite modes.

    reference: sink/SqlSinker.scala:15-106 — DataFrame writes to SQL
    Server via JDBC/connector/bulk-copy with a configured ``table`` and
    ``writeMode``. TPU-native one-box analog: sqlite3 (stdlib DB-API);
    any DB-API driver slots in behind the same conf
    (``output.<name>.sql.{connectionstring,table,writemode}``). Column
    DDL is inferred from the first batch's row shape.
    """

    kind = "sql"

    def __init__(self, connection_string: str, table: str, write_mode: str = "append"):
        # "jdbc:sqlite:/path/db" or a bare path both work
        self.db_path = connection_string.split(":", 2)[-1] if \
            connection_string.startswith("jdbc:") else connection_string
        self.table = table
        self.write_mode = write_mode.lower()
        self._initialized = False
        self._lock = threading.Lock()

    @staticmethod
    def _sql_type(v) -> str:
        if isinstance(v, bool):
            return "INTEGER"
        if isinstance(v, int):
            return "INTEGER"
        if isinstance(v, float):
            return "REAL"
        return "TEXT"

    @staticmethod
    def _q(identifier: str) -> str:
        """Quote an identifier, escaping embedded quotes — column names
        come from row keys, i.e. from data."""
        return '"' + identifier.replace('"', '""') + '"'

    def write(self, dataset, rows, batch_time_ms) -> int:
        if not rows:
            return 0
        import sqlite3

        fs.ensure_parent_dir(self.db_path)
        # union of keys across the batch: later rows may carry extra
        # columns, and later batches may evolve the shape
        cols: List[str] = []
        for r in rows:
            for c in r:
                if c not in cols:
                    cols.append(c)
        sample = {c: next((r[c] for r in rows if c in r), None) for c in cols}
        with self._lock:
            conn = sqlite3.connect(self.db_path, timeout=30)
            try:
                cur = conn.cursor()
                tq = self._q(self.table)
                if not self._initialized:
                    if self.write_mode == "overwrite":
                        cur.execute(f'DROP TABLE IF EXISTS {tq}')
                    ddl = ", ".join(
                        f'{self._q(c)} {self._sql_type(sample[c])}' for c in cols
                    )
                    cur.execute(
                        f'CREATE TABLE IF NOT EXISTS {tq} ({ddl})'
                    )
                    self._initialized = True
                existing = {
                    r[1] for r in cur.execute(
                        f'PRAGMA table_info({tq})'
                    ).fetchall()
                }
                for c in cols:
                    if c not in existing:
                        cur.execute(
                            f'ALTER TABLE {tq} ADD COLUMN '
                            f'{self._q(c)} {self._sql_type(sample[c])}'
                        )
                placeholders = ", ".join("?" for _ in cols)
                quoted = ", ".join(self._q(c) for c in cols)
                cur.executemany(
                    f'INSERT INTO {tq} ({quoted}) VALUES ({placeholders})',
                    [
                        tuple(
                            r.get(c) if isinstance(
                                r.get(c), (int, float, str, bytes, type(None))
                            ) else json.dumps(r.get(c), default=str)
                            for c in cols
                        )
                        for r in rows
                    ],
                )
                conn.commit()
            finally:
                conn.close()
        return len(rows)


class DocumentSink(Sink):
    """Document-store sink: per-row document create with generated ids.

    reference: sink/CosmosDBSinker.scala:19-140 — a DocumentClient pool
    per partition creating one document per row in ``db/collection``.
    One-box analog: an append-only JSONL document log per collection
    under ``<root>/<db>/<collection>/docs.jsonl``, each row gaining a
    GUID ``id`` like Cosmos assigns; a cloud document client slots in
    behind the same conf (``output.<name>.cosmosdb.{connectionstring,
    database,collection}``).
    """

    kind = "cosmosdb"

    def __init__(self, root: str, database: str, collection: str):
        self.dir = os.path.join(root, database, collection)
        self._lock = threading.Lock()

    def write(self, dataset, rows, batch_time_ms) -> int:
        if not rows:
            return 0
        import uuid

        os.makedirs(self.dir, exist_ok=True)
        path = os.path.join(self.dir, "docs.jsonl")
        with self._lock:
            with open(path, "a", encoding="utf-8") as f:
                for r in rows:
                    doc = {"id": str(uuid.uuid4()), **r}
                    f.write(json.dumps(doc, default=str) + "\n")
        return len(rows)


class StreamSink(Sink):
    """Event-stream sink: newline-delimited JSON over TCP.

    reference: sink/EventHubStreamPoster.scala:15-81 — per-row JSON
    posts into an EventHub. TPU-native analog: the DCN egress path is a
    TCP stream in the same wire format SocketSource ingests, so one
    flow's output can feed another's input (EventHub's role between
    chained flows). Reconnects lazily; failures raise so the batch
    retries rather than silently dropping (at-least-once).
    """

    kind = "eventhub"

    def __init__(self, host: str, port: int):
        self.addr = (host, port)
        self._sock = None
        self._lock = threading.Lock()

    def _connect(self):
        import socket as _socket

        s = _socket.create_connection(self.addr, timeout=10)
        return s

    def write(self, dataset, rows, batch_time_ms) -> int:
        if not rows:
            return 0
        payload = b"".join(
            json.dumps(r, default=str).encode() + b"\n" for r in rows
        )
        with self._lock:
            try:
                if self._sock is None:
                    self._sock = self._connect()
                self._sock.sendall(payload)
            except OSError:
                # one reconnect attempt, then propagate for batch retry
                try:
                    if self._sock is not None:
                        self._sock.close()
                except OSError:
                    pass
                self._sock = self._connect()
                self._sock.sendall(payload)
        return len(rows)


class KafkaSink(Sink):
    """Rows out to a Kafka topic — or EventHub through its
    Kafka-compatible endpoint, the reference EventHubStreamPoster's
    transport (sink/EventHubStreamPoster.scala:15-81) in its
    EventHub-over-Kafka form. Uses the dependency-free wire producer
    (runtime/kafka_wire.py), so it works on hosts without a Kafka
    client library; produce errors raise so the batch retries
    (at-least-once)."""

    kind = "kafka"

    def __init__(
        self,
        brokers: str,
        topic: str,
        security=None,
        username=None,
        password=None,
    ):
        from .kafka_wire import WireKafkaProducer

        self._producer = WireKafkaProducer(
            brokers, topic, security=security,
            username=username, password=password,
        )
        self._lock = threading.Lock()

    def write(self, dataset, rows, batch_time_ms) -> int:
        if not rows:
            return 0
        payload = [json.dumps(r, default=str).encode() for r in rows]
        with self._lock:
            self._producer.send(payload)
        return len(rows)

    def close(self) -> None:
        self._producer.close()


class MetricSink(Sink):
    """Routes a dataset's rows into the metrics pipeline.

    Tables with the CreateMetric shape (EventTime/MetricName/Metric/...)
    become metric points named ``<flow>:<MetricName>``; alert tables keep
    full rows for DirectTable widgets. reference: tables OUTPUT ... TO
    Metrics land in Redis via the metric sink path."""

    kind = "metric"

    def __init__(self, metric_logger: MetricLogger):
        self.logger = metric_logger

    def write(self, dataset, rows, batch_time_ms) -> int:
        for r in rows:
            metric_name = r.get("MetricName", dataset)
            uts = r.get("EventTime", batch_time_ms)
            if not isinstance(uts, (int, float)):
                uts = batch_time_ms
            if set(r) >= {"MetricName", "Metric"}:
                self.logger.send_metric(str(metric_name), r.get("Metric"), int(uts))
                if r.get("Pivot1"):
                    self.logger.send_metric_events(str(metric_name), [r], int(uts))
            else:
                self.logger.send_metric_events(str(metric_name), [r], int(uts))
        return len(rows)


@dataclass
class OutputOperator:
    """One named output dataset -> its sinks (OutputManager.scala:96-126)."""

    dataset: str
    sinks: List[Sink] = field(default_factory=list)

    def write(self, rows: List[dict], batch_time_ms: int) -> Dict[str, int]:
        counts = {}
        for s in self.sinks:
            # one span per sink write under the batch trace (no-op when
            # none is active) — makes a slow destination visible per
            # batch instead of hiding inside the "sinks" stage total
            with tracing.span(
                f"sink/{s.kind}", dataset=self.dataset, rows=len(rows)
            ):
                counts[s.kind] = s.write(self.dataset, rows, batch_time_ms)
        return counts


def build_output_operators(
    dict_: SettingDictionary,
    metric_logger: MetricLogger,
    table_sink_map: Dict[str, List[str]],
) -> Dict[str, OutputOperator]:
    """Construct operators from ``datax.job.output.<name>.*`` conf plus the
    codegen's table->sink map (OUTPUT t TO sink).

    table_sink_map: dataset -> [output names]. Conf defines each output
    name's sinks; datasets route to them.
    """
    outputs_conf = dict_.get_sub_dictionary("datax.job.output.").group_by_sub_namespace()
    named_sinks: Dict[str, List[Sink]] = {}
    for out_name, sub in outputs_conf.items():
        sinks: List[Sink] = []
        for sink_kind, sconf in sub.group_by_sub_namespace().items():
            if sink_kind in ("blob", "file"):
                folder = (
                    sconf.get("group.main.folder")
                    or sconf.get("path")
                    or f"/tmp/dxtpu-out/{out_name}"
                )
                compression = sconf.get_or_else("compressiontype", "gzip")
                sinks.append(FileSink(folder, compression))
            elif sink_kind == "httppost":
                headers = {
                    k.split(".", 1)[1]: v
                    for k, v in sconf.dict.items()
                    if k.startswith("header.")
                }
                sinks.append(HttpPostSink(sconf.get_string("endpoint"), headers))
            elif sink_kind == "console":
                sinks.append(ConsoleSink(sconf.get_int_option("maxrows") or 20))
            elif sink_kind in ("externalfn", "azurefunction"):
                sinks.append(ExternalFunctionSink(
                    sconf.get_string("serviceendpoint"),
                    api=sconf.get_or_else("api", ""),
                    code=sconf.get_or_else("code", ""),
                    method=sconf.get_or_else("methodtype", "post"),
                ))
            elif sink_kind == "metric":
                sinks.append(MetricSink(metric_logger))
            elif sink_kind == "sql":
                sinks.append(SqlSink(
                    sconf.get_string("connectionstring"),
                    sconf.get_or_else("table", out_name),
                    sconf.get_or_else("writemode", "append"),
                ))
            elif sink_kind in ("cosmosdb", "document"):
                sinks.append(DocumentSink(
                    sconf.get_or_else("connectionstring", "/tmp/dxtpu-docs"),
                    sconf.get_or_else("database", "db"),
                    sconf.get_or_else("collection", out_name),
                ))
            elif sink_kind in ("kafka", "eventhubkafka", "eventhub-kafka"):
                # conf: datax.job.output.<n>.kafka.{bootstrapservers,topic,
                # security,username,password}; the eventhub flavor (same
                # spelling as inputtype=eventhub-kafka) defaults the SASL
                # triplet to the EventHub Kafka-endpoint convention
                username = sconf.get("username")
                password = sconf.get("password")
                security = sconf.get("security")
                if sink_kind != "kafka":
                    security = security or "sasl_ssl"
                    username = username or "$ConnectionString"
                    password = password or sconf.get("connectionstring")
                sinks.append(KafkaSink(
                    sconf.get_or_else("bootstrapservers", "localhost:9092"),
                    sconf.get_or_else("topic", out_name),
                    security=security,
                    username=username,
                    password=password,
                ))
            elif sink_kind in ("eventhub", "stream"):
                # connection "host:port" (EventHub conn-string role); any
                # other shape (e.g. an sb:// conn string from a reference
                # conf) degrades to a file sink like one-box
                conn = sconf.get("connectionstring") or ""
                h, _, p = conn.rpartition(":")
                if p.isdigit():
                    sinks.append(StreamSink(h or "127.0.0.1", int(p)))
                else:
                    logger.warning(
                        "eventhub sink for output %s has no host:port; "
                        "writing to file sink instead", out_name,
                    )
                    sinks.append(FileSink(f"/tmp/dxtpu-out/{out_name}", "gzip"))
        if not sinks and out_name.lower() == "metrics":
            sinks.append(MetricSink(metric_logger))
        named_sinks[out_name] = sinks

    operators: Dict[str, OutputOperator] = {}
    for dataset, out_names in table_sink_map.items():
        op = OutputOperator(dataset)
        for on in out_names:
            if on.lower() == "metrics" and on not in named_sinks:
                op.sinks.append(MetricSink(metric_logger))
            else:
                op.sinks.extend(named_sinks.get(on, []))
        operators[dataset] = op
    return operators


class OutputDispatcher:
    """Parallel fan-out over output operators (the ``.par`` at
    CommonProcessorFactory.scala:311-314); emits per-sink count metrics
    (Sink_<kind> — OutputManager.scala:122).

    The fan-out runs on ONE persistent executor instead of spawning a
    thread per operator per batch: under the hosts' depth-N pipelined
    loops, batch N-1's sink I/O lands on already-warm workers while
    batch N's device step runs, so per-batch thread startup never sits
    on the critical path."""

    def __init__(
        self,
        operators: Dict[str, OutputOperator],
        metric_logger: MetricLogger,
        max_workers: Optional[int] = None,
    ):
        from concurrent.futures import ThreadPoolExecutor

        self.operators = operators
        self.metric_logger = metric_logger
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers or max(1, min(8, len(operators) or 1)),
            thread_name_prefix="sink",
        )

    def dispatch(
        self, datasets: Dict[str, List[dict]], batch_time_ms: int
    ) -> Dict[str, int]:
        results: Dict[str, int] = {}
        lock = threading.Lock()
        errors: List[BaseException] = []
        # carry the caller's batch trace onto the fan-out workers, so
        # per-sink spans parent under the host's "sinks" span
        trace_pos = tracing.capture()

        def run_op(name: str, op: OutputOperator, rows: List[dict]):
            try:
                with tracing.activated(trace_pos):
                    counts = op.write(rows, batch_time_ms)
            except BaseException as e:  # noqa: BLE001 — re-raised after wait
                with lock:
                    errors.append(e)
                return
            with lock:
                for kind, c in counts.items():
                    results[f"{MetricName.MetricSinkPrefix}{kind}"] = (
                        results.get(f"{MetricName.MetricSinkPrefix}{kind}", 0) + c
                    )

        futures = [
            self._pool.submit(run_op, name, op, datasets.get(name, []))
            for name, op in self.operators.items()
        ]
        for f in futures:
            f.result()  # run_op never raises; this is the join barrier
        if errors:
            # propagate so the host's batch try/except retries the batch
            # instead of checkpointing past lost events (at-least-once)
            raise errors[0]
        for metric, count in results.items():
            self.metric_logger.send_metric(metric, count, batch_time_ms)
        return results

    def close(self) -> None:
        """Shut the fan-out pool down (host stop path); idempotent."""
        self._pool.shutdown(wait=False, cancel_futures=True)
