"""Buffer sanitizer — the DYNAMIC half of the DX8xx buffer-lifetime
story (``analysis/racecheck.py`` is the static half).

The bug class (PRs 8/13/14 each found one): on the CPU backend
``jnp.asarray``/``np.asarray`` of a 64-byte-aligned buffer is a
zero-copy VIEW. The engine deliberately exploits that for ingest (the
``PackedBufferPool`` matrices are donated straight into the step), so a
view that outlives its buffer's donation/release reads freed-for-reuse
memory — silent corruption on a good day, a segfault on a bad one.

AddressSanitizer-style defense, adapted to what can be safely written:

* **Pool slots** are poisoned with a sentinel pattern the moment they
  are released (``PackedBufferPool.release`` calls ``poison`` when a
  sanitizer is attached). The pool owns a released matrix — nobody may
  legitimately read it — so any sentinel that later surfaces in a sink
  payload or checkpoint is a use-after-release caught red-handed.
* **Donated ring buffers** cannot be poisoned: after donation the
  memory belongs to XLA (writing it would corrupt live device state —
  the very bug we hunt). They are guarded by ALIAS checks instead:
  ``check_snapshot`` asserts a window-state checkpoint shares no memory
  with the live rings (a real copy never does; the PR 13 bug — a
  dropped ``copy=True`` — trips it on the first checkpoint).
* **Sink payloads / checkpoints** are scanned for sentinel runs
  (``scan_table`` / ``check_snapshot``): >= ``MIN_RUN`` consecutive
  sentinel words is no plausible payload, it is a poisoned slot leaking
  through a zero-copy view.

Every hit becomes a runtime **DX805** event — drained by the host into
the flight recorder beside conformance drift — and bumps
``Sanitizer_PoisonHit_Count``; everything the sanitizer guarded bumps
``Sanitizer_GuardedViews_Count``. Armed via conf
``datax.job.process.debug.buffersanitizer`` (a debug mode: poisoning
costs one memset per released slot — bench.py's ``sanitizer`` block
keeps the overhead a committed number).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

# 0x5A5A5A5A: the classic poison byte pattern (ASan uses 0xbe/0xbd
# regions; 'Z' bytes read obviously-wrong in both int32 and f32 views)
SENTINEL = np.int32(0x5A5A5A5A)
# a single sentinel word can occur in honest data; four consecutive
# words (16 bytes) cannot, outside astronomically unlucky payloads
MIN_RUN = 4


def _longest_sentinel_run(arr: np.ndarray) -> int:
    """Longest run of consecutive SENTINEL words in ``arr`` viewed as
    int32 (0 when the dtype is not 4-byte or nothing matches)."""
    try:
        a = np.ascontiguousarray(arr)
    except Exception:  # noqa: BLE001 — exotic array-likes never fail a scan
        return 0
    if a.dtype.itemsize != 4 or a.size < MIN_RUN:
        return 0
    flat = a.view(np.int32).ravel()
    idx = np.flatnonzero(flat == SENTINEL)
    if idx.size < MIN_RUN:
        return 0
    # split the match positions into consecutive runs
    breaks = np.flatnonzero(np.diff(idx) != 1)
    best = 0
    start = 0
    for b in list(breaks) + [idx.size - 1]:
        best = max(best, int(b - start + 1))
        start = b + 1
    return best


class BufferSanitizer:
    """Poison released pool slots; scan outputs/checkpoints for leaks.

    Thread-safe: poisoning happens on whatever thread releases a slot
    (dispatch or landing), scans run on the landing thread, and the
    host drains events/metrics at collect time.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.poison_count = 0       # slots poisoned (lifetime)
        self.guarded_views = 0      # buffers guarded: poisons + scans
        self.poison_hits = 0        # DX805s fired (lifetime)
        self._events: List[Dict[str, object]] = []
        self._hits_drained = 0
        self._guarded_drained = 0

    # -- the poisoning half (pool release hook) ---------------------------
    def poison(self, matrix: np.ndarray) -> None:
        """Overwrite a RELEASED pool matrix with the sentinel. Safe by
        ownership: the pool holds the only legitimate reference."""
        try:
            matrix.fill(SENTINEL)
        except (ValueError, AttributeError):
            return  # read-only or non-ndarray: nothing to guard
        with self._lock:
            self.poison_count += 1
            self.guarded_views += 1

    # -- the scanning half ------------------------------------------------
    def check_snapshot(
        self, snap: Dict[str, object], window_buffers: Dict[str, object],
    ) -> int:
        """Guard a ``snapshot_window_state`` result: every saved array
        must be a REAL copy (no shared memory with the live rings) and
        sentinel-free. Returns the number of new hits."""
        before = self.poison_hits
        rings = snap.get("rings", {}) if isinstance(snap, dict) else {}
        for table, saved in rings.items():
            live = window_buffers.get(table)
            arrays = dict(saved.get("cols", {}))
            arrays["__valid__"] = saved.get("valid")
            for cname, a in arrays.items():
                if a is None:
                    continue
                with self._lock:
                    self.guarded_views += 1
                run = _longest_sentinel_run(a)
                if run >= MIN_RUN:
                    self._record(
                        kind="sentinel-run", where="checkpoint",
                        table=table, column=cname, run=run,
                    )
                if live is None:
                    continue
                live_arr = (
                    live.valid if cname == "__valid__"
                    else live.cols.get(cname)
                )
                if live_arr is None:
                    continue
                try:
                    # dx-race: allow-zero-copy read-only identity probe —
                    # the view dies inside this call, nothing escapes
                    aliased = np.shares_memory(a, np.asarray(live_arr))
                except Exception:  # noqa: BLE001 — non-CPU backends copy
                    aliased = False
                if aliased:
                    self._record(
                        kind="snapshot-alias", where="checkpoint",
                        table=table, column=cname, run=0,
                    )
        return self.poison_hits - before

    def scan_table(self, name: str, table) -> int:
        """Scan one landed host output table (sink payload) for
        sentinel leakage. Returns the number of new hits."""
        before = self.poison_hits
        arrays = dict(getattr(table, "cols", {}) or {})
        valid = getattr(table, "valid", None)
        if valid is not None:
            arrays["__valid__"] = valid
        for cname, a in arrays.items():
            with self._lock:
                self.guarded_views += 1
            run = _longest_sentinel_run(np.asarray(a))
            if run >= MIN_RUN:
                self._record(
                    kind="sentinel-run", where="sink", table=name,
                    column=cname, run=run,
                )
        return self.poison_hits - before

    # -- event/metric drains (host collect cadence) -----------------------
    def _record(self, kind: str, where: str, table: str, column: str,
                run: int) -> None:
        with self._lock:
            self.poison_hits += 1
            self._events.append({
                "code": "DX805",
                "kind": kind,
                "where": where,
                "table": str(table),
                "column": str(column),
                "runLength": int(run),
                "message": (
                    f"DX805: {kind} in {where} table {table!r} column "
                    f"{column!r}"
                    + (f" ({run} sentinel words)" if run else "")
                    + " — a donated/pooled buffer view outlived its "
                    "buffer (use-after-release)"
                ),
            })

    def drain_events(self) -> List[Dict[str, object]]:
        """DX805 events since the last drain (flight-recorder feed)."""
        with self._lock:
            events, self._events = self._events, []
        return events

    def drain_metric_deltas(self) -> Dict[str, float]:
        """Sanitizer_* metric deltas since the last drain; hit count is
        only reported once nonzero (silence == health, like the other
        incident counters)."""
        with self._lock:
            hits = self.poison_hits - self._hits_drained
            self._hits_drained = self.poison_hits
            guarded = self.guarded_views - self._guarded_drained
            self._guarded_drained = self.guarded_views
        out: Dict[str, float] = {}
        if guarded:
            out["Sanitizer_GuardedViews_Count"] = float(guarded)
        if hits:
            out["Sanitizer_PoisonHit_Count"] = float(hits)
        return out


def from_conf(dbg_conf) -> Optional[BufferSanitizer]:
    """``datax.job.process.debug.buffersanitizer=true`` arms the
    sanitizer (``dbg_conf`` is the ``debug.`` sub-dictionary)."""
    # dx-conf: read debug.buffersanitizer default=false
    flag = (dbg_conf.get_or_else("buffersanitizer", "false") or "").lower()
    return BufferSanitizer() if flag == "true" else None
