"""The engine core: build and run a flow's per-batch processing step.

reference: datax-host processor/CommonProcessorFactory.scala:42-660 —
init loads schema/projections/transform/refdata/UDFs, then per batch:
``project()`` raw->typed projection (:90-103), ``route()`` SQL pipeline +
time windows + state tables + outputs (:131-328), ``processDataset()``
orchestration + metrics (:333-399).

TPU-native shape: everything device-side — projection, ring-buffer
window update, the whole SQL pipeline, state-table production and count
metrics — compiles into ONE jitted step function. The host loop only
encodes ingest, invokes the step, materializes output datasets, and runs
sinks/checkpoints.

Multi-source flows (reference: the ``input.sources`` map in
flattenerConfig.json and the per-source grouping in
input/BlobPointerInput.scala:30-160): ``datax.job.input.sources.<name>.*``
declares N named sources, each with its own schema and projection into
its own named table; time windows may target any of those tables, so a
flow can join two independent streams across sliding windows — all still
inside the single jitted step.
"""

from __future__ import annotations

import functools
import json
import logging
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..compile.pipeline import Pipeline, PipelineCompiler, parse_state_table_schema
from ..compile.planner import PlannerConfig, TableData, ViewSchema
from ..compile.sqlparser import parse_select
from ..compile.transform_parser import TransformParser
from ..constants import ColumnName, DatasetName
from ..core.config import EngineException, SettingDictionary, SettingNamespace
from ..obs.tracing import span as _trace_span
from ..core.schema import ColType, Schema, StringDictionary
from .materialize import materialize_rows
from .statetable import StateTable
from .timewindow import (
    WindowBuffers,
    make_buffers,
    num_slots,
    update_buffers,
    window_table,
)

logger = logging.getLogger(__name__)

# default in-flight window of the pipelined hosts (conf
# datax.job.process.pipeline.depth): decode/dispatch of batch N+k
# proceeds while up to `depth` earlier batches compute and their D2H
# copies land; finish/commit stays strictly FIFO
DEFAULT_PIPELINE_DEPTH = 2

# sized output transfer: adapt the per-output D2H copy to the rows a
# flow actually produces (EWMA of observed counts, bucketed to powers
# of two) instead of the full padded capacity
TRANSFER_EWMA_ALPHA = 0.25
TRANSFER_HEADROOM = 4  # sized cap >= HEADROOM * EWMA (burst absorption)
MIN_TRANSFER_ROWS = 256  # below this, shrinking saves nothing
# after an overflow re-fetch, the output's headroom factor doubles for
# the next N batches so back-to-back bursts can't thrash the two-phase
# fallback (the EWMA jump alone only covers the observed count, not a
# still-climbing one)
OVERFLOW_BOOST_FACTOR = 2
OVERFLOW_BOOST_BATCHES = 8

# donated double-buffered output slots: the jitted slot-pack writes each
# output's transfer view into one of two resident, transfer-ready buffer
# sets per (output, capacity bucket), alternating A/B so batch N+1's
# step never clobbers batch N's in-flight background D2H copy
OUTPUT_SLOT_BUFFERS = 2

# donation contract of the fused step jit: the window rings (positional
# arg 1) are donated so XLA updates them in place; nothing else is.
# The compile-surface analyzer (analysis/compilecheck.py) records this
# pattern per manifest entry — DX602 fires when a shipped manifest
# disagrees with it.
STEP_DONATE_ARGNUMS = (1,)

# bound on the per-capacity-bucket jit caches of the transfer helpers
# (_slice_table/_pack_slot): one jitted closure per (helper kind, pow2
# capacity bucket), LRU-evicted above this cap so a wandering EWMA can
# never grow the cache forever. Conf datax.job.process.compile.
# jitcachecap overrides; the DX601 compile-surface lint uses the SAME
# constant to flag flows whose reachable bucket lattice alone already
# exceeds the bound (analysis/compilecheck.py).
DEFAULT_JIT_CACHE_CAP = 32

_CTYPE_TO_PLAN = {
    ColType.LONG: "long",
    ColType.DOUBLE: "double",
    ColType.BOOLEAN: "boolean",
    ColType.STRING: "string",
    ColType.TIMESTAMP: "timestamp",
}


def schema_to_view(schema: Schema) -> ViewSchema:
    return ViewSchema({c.name: _CTYPE_TO_PLAN[c.ctype] for c in schema.columns})


def default_projection(schema: Schema, timestamp_column: Optional[str]) -> str:
    """The HomeAutomation normalization snippet shape
    (gui.input.properties.normalizationSnippet) used when a source
    declares no projection of its own."""
    lines = ["Raw.*"]
    if timestamp_column and not schema.has(timestamp_column):
        lines.insert(0, f"current_timestamp() AS {timestamp_column}")
    return "\n".join(lines)


def projection_select(step_text: str, from_table: str):
    """One projection step (selectExpr lines) -> parsed Select
    (handler/ProjectionHandler.scala semantics)."""
    items = [
        ln.strip()
        for ln in step_text.replace("\r", "").split("\n")
        if ln.strip() and not ln.strip().startswith("--")
    ]
    return parse_select("SELECT " + ", ".join(items) + f" FROM {from_table}")


def window_target(wname: str, targets: List[str]) -> str:
    """Bind a window name to its projected table: the longest target
    ``T`` such that the window is named ``T_<duration>``. A
    single-source flow may name windows freely (they can only mean
    its one table); multi-source flows must prefix-match or set the
    window's ``table`` conf key."""
    best = ""
    for t in targets:
        if wname.startswith(t + "_") and len(t) > len(best):
            best = t
    if best:
        return best
    return targets[0] if len(targets) == 1 else ""


def _read_maybe_file(value: str) -> str:
    """Conf values may inline content or point at a file (the reference
    always loads from storage; one-box flows inline the schema JSON).
    ``objstore://`` URLs fetch from the shared object store — the path
    shape a control plane on another host generates."""
    if value is None:
        return None
    v = value.strip()
    if v.startswith("{") or v.startswith("[") or "\n" in v or "--" in v[:4]:
        return value
    if v.startswith("objstore://") or v.startswith("objstore+https://"):
        from ..utils.fs import read_text

        return read_text(v)
    if os.path.exists(v):
        with open(v, "r", encoding="utf-8") as f:
            return f.read()
    return value


def load_reference_data_tables(
    dict_: SettingDictionary, dictionary: StringDictionary
) -> Dict[str, Tuple[ViewSchema, TableData]]:
    """CSV reference data as joinable tables
    (reference: handler/ReferenceDataHandler.scala:17-66)."""
    import csv

    out: Dict[str, Tuple[ViewSchema, TableData]] = {}
    groups = dict_.group_by_sub_namespace(
        SettingNamespace.JobInputPrefix + "referencedata."
    )
    for name, sub in groups.items():
        path = sub.get_string("path")
        delimiter = sub.get_or_else("delimiter", ",") or ","
        header = (sub.get_or_else("header", "true") or "true").lower() == "true"
        with open(path, "r", encoding="utf-8") as f:
            reader = csv.reader(f, delimiter=delimiter)
            rows = [r for r in reader if r]
        if not rows:
            continue
        if header:
            col_names, data_rows = rows[0], rows[1:]
        else:
            col_names = [f"_c{i}" for i in range(len(rows[0]))]
            data_rows = rows
        types: Dict[str, str] = {}
        for j, cname in enumerate(col_names):
            vals = [r[j] for r in data_rows if j < len(r)]
            types[cname] = _infer_csv_type(vals)
        cols: Dict[str, jnp.ndarray] = {}
        n = len(data_rows)
        for j, cname in enumerate(col_names):
            t = types[cname]
            if t == "long":
                arr = np.array([int(r[j]) for r in data_rows], dtype=np.int32)
            elif t == "double":
                arr = np.array([float(r[j]) for r in data_rows], dtype=np.float32)
            else:
                arr = np.array(
                    [dictionary.encode(r[j]) for r in data_rows], dtype=np.int32
                )
            cols[cname] = jnp.asarray(arr)
        table = TableData(cols, jnp.ones((n,), dtype=jnp.bool_))
        out[name] = (ViewSchema(types), table)
    return out


def _infer_csv_type(vals: List[str]) -> str:
    try:
        for v in vals:
            int(v)
        return "long"
    except ValueError:
        pass
    try:
        for v in vals:
            float(v)
        return "double"
    except ValueError:
        return "string"


@jax.tree_util.register_pytree_node_class
@dataclass
class PackedRaw:
    """One-matrix host->device transfer of a raw batch.

    On split hosts (TPU behind a network tunnel) each host->device array
    costs a transfer op; a 7-column batch pays 7. Packing every 4-byte
    column into rows of ONE [n_cols+1, capacity] int32 matrix (floats
    bitcast, bools widened, validity as the last row) makes ingest a
    single contiguous transfer; the jitted step bitcasts/slices the rows
    back apart device-side, which XLA fuses to nothing.
    """

    data: jnp.ndarray  # [len(layout)+1, capacity] int32; last row = valid
    layout: Tuple[Tuple[str, str], ...]  # (column, kind: i32|f32|bool)

    def tree_flatten(self):
        return (self.data,), self.layout

    @classmethod
    def tree_unflatten(cls, layout, children):
        return cls(children[0], layout)

    def unpack(self) -> TableData:
        """Device-side (traceable) split back into named columns."""
        cols: Dict[str, jnp.ndarray] = {}
        for i, (name, kind) in enumerate(self.layout):
            row = self.data[i]
            if kind == "f32":
                row = jax.lax.bitcast_convert_type(row, jnp.float32)
            elif kind == "bool":
                row = row != 0
            cols[name] = row
        return TableData(cols, self.data[len(self.layout)] != 0)


def pack_raw(
    np_cols: Dict[str, np.ndarray], valid: np.ndarray,
    to_device: bool = True,
) -> PackedRaw:
    """Stack host columns into the single-transfer matrix (cheap host
    memcpy; the win is one device transfer instead of n_cols+1).

    ``to_device=False`` keeps the matrix as numpy — the jitted step's
    call transfers it implicitly — so a decode-ahead worker thread can
    build batches without touching jax from off the main thread."""
    rows: List[np.ndarray] = []
    layout: List[Tuple[str, str]] = []
    for c, a in np_cols.items():
        if a.dtype == np.float32:
            kind = "f32"
            a = a.view(np.int32)
        elif a.dtype == np.float64:
            kind = "f32"
            a = a.astype(np.float32).view(np.int32)
        elif a.dtype == np.bool_:
            kind = "bool"
            a = a.astype(np.int32)
        else:
            kind = "i32"
            if a.dtype != np.int32:
                a = a.astype(np.int32)  # x64-off semantics: wrap like jnp
        rows.append(a)
        layout.append((c, kind))
    rows.append(valid.astype(np.int32))
    stacked = np.stack(rows)
    return PackedRaw(
        jnp.asarray(stacked) if to_device else stacked, tuple(layout)
    )


def pack_from_matrix(
    matrix: np.ndarray, layout: Tuple[Tuple[str, str], ...],
    to_device: bool = True,
) -> PackedRaw:
    """PackedRaw over an ALREADY-packed matrix — the zero-copy sibling
    of ``pack_raw`` for the native decoder's pooled ingest buffers,
    which are written in the transfer layout to begin with. On the CPU
    backend ``jnp.asarray`` of the 64-byte-aligned pool matrix is a
    zero-copy view, which is exactly why the pool may only reuse a
    matrix after its batch has landed (PendingBatch slot release)."""
    # dx-race: param matrix=pool
    # dx-race: allow-zero-copy THE designed pooled zero-copy ingest site;
    # lifetime pinned by the PendingBatch owner-handoff
    return PackedRaw(
        jnp.asarray(matrix) if to_device else matrix, tuple(layout)
    )


def build_step_fn(
    ts_col: Optional[str],
    windows: Dict[str, Tuple[str, float]],
    output_datasets: List[str],
    state_names: List[str],
    refdata_names: List[str],
    ring_tables: List[str],
    pipeline,
    source_targets: List[Tuple[str, str]],  # (source name, target table)
    proj_views: Dict[str, list],
    primary_target: str,
):
    """Build the fused per-batch step function from its compiled parts.

    The ONE definition of the whole-flow device program: ``FlowProcessor
    ._jit_step`` jits exactly this, and the compile-surface analyzer
    (``analysis/compilecheck.py``) lowers exactly this over eval_shape
    avals to prove the trace surface closed — sharing the builder is
    what makes the emitted compile manifest drift-free by construction
    (the DX603 byte-exactness contract)."""

    def step(
        raw: Dict[str, TableData],
        rings: Dict[str, WindowBuffers],
        state: Dict[str, TableData],
        refdata: Dict[str, TableData],
        base_s: jnp.ndarray,
        now_rel_ms: jnp.ndarray,
        counter: jnp.ndarray,
        delta_ms: jnp.ndarray,
        aux: Dict[str, jnp.ndarray],
    ):
        # 1. per-source projection into its target table (each source
        # gets its own env so `Raw` binds to ITS raw table)
        projected: Dict[str, TableData] = {}
        for sname_, target_ in source_targets:
            rt = raw[sname_]
            if isinstance(rt, PackedRaw):
                rt = rt.unpack()  # split the single-transfer matrix
            env: Dict[str, TableData] = {
                "Raw": rt,
                DatasetName.DataStreamRaw: rt,
                "__aux": aux,
            }
            for v in proj_views[sname_]:
                env[v.name] = v.fn(env, base_s, now_rel_ms)
            projected[target_] = env[target_]

        # 2. ring updates (one ring per windowed table; each ring's
        # slot index derives from the shared batch counter)
        new_rings: Dict[str, WindowBuffers] = {}
        for table in ring_tables:
            buf = rings[table]
            slot = jax.lax.rem(
                counter, jnp.asarray(buf.valid.shape[0], jnp.int32)
            )
            new_rings[table] = update_buffers(
                buf, projected[table], slot, delta_ms, ts_col
            )

        tables: Dict[str, TableData] = dict(projected)
        for wname, (table, dur_s) in windows.items():
            tables[wname] = window_table(
                new_rings[table], int(dur_s * 1000), now_rel_ms, ts_col
            )
        for rname in refdata_names:
            tables[rname] = refdata[rname]
        for sname in state_names:
            tables[sname] = state[sname]

        out = pipeline.run(tables, base_s, now_rel_ms, aux=aux)

        new_state = {n: out.get(n, state[n]) for n in state_names}

        # compact outputs device-side (valid rows to the front) so the
        # host transfers only [:count] rows — the device->host hop is
        # the expensive boundary (a network tunnel on split hosts),
        # so bytes AND round-trips are minimized: all per-batch
        # scalars ride ONE packed vector.
        from ..ops.compact import compact_indices

        datasets = {}
        counts = [projected[primary_target].count()]
        for n in output_datasets:
            t = out[n]
            idx, ov = compact_indices(t.valid, t.valid.shape[0])
            datasets[n] = TableData(
                {c: v[idx] if v.shape[:1] == t.valid.shape else v
                 for c, v in t.cols.items()},
                ov,
            )
            counts.append(t.count())
        # fixed layout: per output one groups-overflow then one
        # join-overflow slot; -1 marks "output does not track this
        # overflow" so the host can keep emitting 0 for ones that do
        for key in ("__overflow.groups", "__overflow.joins"):
            for n in output_datasets:
                counts.append(
                    out[n].cols[key][0]
                    if key in out[n].cols
                    else jnp.asarray(-1, jnp.int32)
                )
        # per-target projected input counts (multi-source metrics)
        for _sname, target_ in source_targets:
            counts.append(projected[target_].count())
        counts_vec = jnp.stack(
            [jnp.asarray(c, jnp.int32) for c in counts]
        )
        # plain tuple of pytrees for the jit boundary
        return (datasets, new_rings, new_state, counts_vec)

    return step


def transfer_buckets(full_cap: int) -> List[int]:
    """Every sized-transfer capacity an output of padded capacity
    ``full_cap`` can ever be fetched at: the pow2 lattice
    ``transfer_capacity`` buckets to (engaging only while the sized cap
    at least halves the copy), plus the full capacity itself (the
    pre-EWMA / overflow / sized-off fetch). Finite by construction —
    the compile manifest enumerates the ``_slice_table``/``_pack_slot``
    entries per bucket from this same lattice, and DX601 fires when it
    alone outgrows the helper jit-cache bound."""
    caps: List[int] = []
    c = _pow2_ceil(MIN_TRANSFER_ROWS)
    while c * 2 <= full_cap:
        caps.append(c)
        c *= 2
    caps.append(int(full_cap))
    return caps


def source_raw_form(input_type: Optional[str], mesh=None) -> str:
    """``packed`` when production dispatch ships a source of this input
    type as the single-matrix PackedRaw (native decoder hot path:
    single chip, non-local input), else ``columns``. The ONE definition
    both the runtime (``FlowProcessor._source_raw_form``) and the
    compile-surface analyzer use — the raw form is part of the step's
    trace signature, so the two may never disagree."""
    from ..native import native_available

    itype = (input_type or "local").lower()
    if mesh is not None or itype in ("", "local"):
        return "columns"
    return "packed" if native_available() else "columns"


# raw-schema type -> PackedRaw row kind (the bitcast pack_raw applies)
_PACK_KINDS = {"double": "f32", "boolean": "bool"}
# raw-schema type -> the numpy dtype the ingest encoders materialize
_RAW_NP_DTYPES = {"double": np.float32, "boolean": np.bool_}


def packed_raw_layout(raw_types: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    """The PackedRaw layout the ingest hot path builds for a raw schema
    (column order preserved; kinds per the pack_raw bitcast rules).
    Layout is pytree aux data, i.e. part of the step's jit cache key —
    the compile manifest derives it from the same map."""
    return tuple(
        (c, _PACK_KINDS.get(t, "i32")) for c, t in raw_types.items()
    )


def packed_raw_struct(raw_types: Dict[str, str], capacity: int) -> PackedRaw:
    """Abstract (ShapeDtypeStruct) PackedRaw for one source — the exact
    aval the jitted step sees on the packed ingest path."""
    layout = packed_raw_layout(raw_types)
    return PackedRaw(
        jax.ShapeDtypeStruct((len(layout) + 1, capacity), jnp.int32), layout
    )


def aval_signature(tree) -> dict:
    """Canonical, JSON-stable description of a pytree of avals: the
    treedef repr (which carries custom-node aux data like the PackedRaw
    layout — part of the jit cache key) plus every leaf's shape and
    dtype. Two entries trace-compatible <=> identical signatures."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return {
        "tree": str(treedef),
        "leaves": [
            [list(map(int, l.shape)), str(np.dtype(l.dtype))] for l in leaves
        ],
    }


def compile_entries_from_avals(
    step_avals: tuple,
    out_avals: Dict[str, TableData],
    sized: bool,
    slots: bool,
) -> List[dict]:
    """Enumerate every jit entry point a flow dispatches — the fused
    step plus one ``_slice_table``/``_pack_slot`` per (output, capacity
    bucket) — as manifest-shaped dicts. Shared by the runtime
    (``FlowProcessor.derive_compile_entries``, which feeds the AOT
    warm) and the static analyzer (``analysis/compilecheck.py``, which
    emits the manifest), so the two can only disagree when the flow
    itself changed (the DX603 drift signal)."""
    entries: List[dict] = [{
        "entry": "step",
        "donate": list(STEP_DONATE_ARGNUMS),
        "static": {},
        "avals": aval_signature(step_avals),
    }]
    for name in sorted(out_avals):
        t = out_avals[name]
        full_cap = int(t.valid.shape[0])
        sliceable = all(
            tuple(v.shape[:1]) == tuple(t.valid.shape)
            for v in t.cols.values()
        )
        caps = transfer_buckets(full_cap) if sized else [full_cap]
        for cap in caps:
            if slots and sliceable:
                entries.append({
                    "entry": f"slice:{name}:{cap}",
                    "donate": [],
                    "static": {"cap": cap},
                    "avals": aval_signature(t),
                })
                slot_aval = jax.eval_shape(
                    functools.partial(_slice_impl, cap=cap), t
                )
                entries.append({
                    "entry": f"pack:{name}:{cap}",
                    "donate": [1],
                    "static": {"cap": cap},
                    "avals": aval_signature((t, slot_aval)),
                })
            elif cap < full_cap:
                entries.append({
                    "entry": f"slice:{name}:{cap}",
                    "donate": [],
                    "static": {"cap": cap},
                    "avals": aval_signature(t),
                })
    return entries


@dataclass
class SourceSpec:
    """One named input stream of a flow: its schema, projection chain,
    the table its projected rows land in, and its batch capacity.

    reference: one entry of the flattener's ``input.sources`` map
    (DataX.Config.Local/Resources/flattenerConfig.json) — per-source
    schema + normalization snippet + target table.
    """

    name: str
    target: str
    schema: Schema
    raw_schema: ViewSchema
    projection_steps: List[str]
    capacity: int
    conf: SettingDictionary


DEFAULT_SOURCE = "default"


class FlowProcessor:
    """Compiled per-flow processor. Build once; call process_batch per
    micro-batch (the closure the reference builds at
    CommonProcessorFactory.scala:50-120)."""

    def __init__(
        self,
        dict_: SettingDictionary,
        dictionary: Optional[StringDictionary] = None,
        udfs: Optional[dict] = None,
        batch_capacity: Optional[int] = None,
        output_datasets: Optional[List[str]] = None,
        mesh=None,
    ):
        self.dict = dict_
        self.dictionary = dictionary or StringDictionary()
        # dictionary capacity bound (see StringDictionary.__init__) —
        # applied even to an injected shared dictionary so the flow conf
        # stays authoritative
        sd_conf = dict_.get_sub_dictionary(
            SettingNamespace.JobProcessPrefix + "stringdictionary."
        )
        maxsize = sd_conf.get_int_option("maxsize")
        if maxsize is not None:
            if maxsize < 1:
                raise EngineException(
                    f"process.stringdictionary.maxsize must be >= 1, "
                    f"got {maxsize}"
                )
            self.dictionary.max_size = maxsize
        if (sd_conf.get_or_else("strict", "false") or "").lower() == "true":
            self.dictionary.strict = True
        # conf-declared UDFs (jar.udf/jar.udaf namespaces) + direct ones;
        # reference: ExtendedUDFHandler/JarUDFHandler reflection loading
        from ..udf import load_udfs_from_conf

        self.udfs = {**load_udfs_from_conf(dict_), **(udfs or {})}
        self.mesh = mesh

        input_conf = dict_.get_sub_dictionary(SettingNamespace.JobInputPrefix)
        process_conf = dict_.get_sub_dictionary(SettingNamespace.JobProcessPrefix)
        self.process_conf = process_conf
        # designer chip count (jobNumChips -> guiJobNumChips -> S650
        # process.numchips): honored when no mesh was passed in,
        # clamped to the locally visible devices so a conf generated
        # for an 8-chip slice still boots on a one-box dev host (a
        # clamp to 1 keeps the packed single-device path).
        if self.mesh is None:
            chips = process_conf.get_int_option("numchips")
            if chips is not None and chips > 1:
                from ..dist.mesh import make_mesh

                n = min(chips, len(jax.devices()))
                if n > 1:
                    if n < chips:
                        logger.warning(
                            "process.numchips=%d clamped to %d visible "
                            "devices", chips, n,
                        )
                    self.mesh = make_mesh(n)

        # sanitizer wiring — the runtime counterpart of the DX3xx UDF
        # analyzer: conf process.debug.nans / process.debug.tracerleaks
        # arm jax.debug_nans and tracer-leak checking around the jitted
        # step, turning surviving UDF impurity (NaNs from bad math,
        # tracers stashed in closures/globals) into loud failures in
        # test jobs instead of silent corruption
        dbg_conf = process_conf.get_sub_dictionary("debug.")
        self.debug_nans = (
            dbg_conf.get_or_else("nans", "false") or ""
        ).lower() == "true"
        self.debug_tracer_leaks = (
            dbg_conf.get_or_else("tracerleaks", "false") or ""
        ).lower() == "true"
        # process.debug.buffersanitizer arms the dynamic half of the
        # DX8xx buffer-lifetime defense (runtime/sanitizer.py): released
        # pool slots are poisoned, sink payloads and window checkpoints
        # scanned for leakage; hits fire runtime DX805
        from .sanitizer import from_conf as _sanitizer_from_conf

        self.buffer_sanitizer = _sanitizer_from_conf(dbg_conf)
        # on_interval failures skipped this/previous batches, drained
        # into the DATAX-<flow>:UdfRefreshError metric at collect()
        self.udf_refresh_errors = 0

        # pipelining + sized output transfer conf
        # (datax.job.process.pipeline.*): `depth` is the in-flight
        # window of the pipelined hosts; `sizedtransfer` adapts the
        # per-output D2H copy to observed row counts (off under a mesh,
        # whose sharded outputs would gather on the slice)
        pipe_conf = process_conf.get_sub_dictionary("pipeline.")
        depth = pipe_conf.get_int_option("depth")
        if depth is None:
            depth = DEFAULT_PIPELINE_DEPTH
        elif depth < 1:
            raise EngineException(
                f"process.pipeline.depth must be >= 1, got {depth}"
            )
        self.pipeline_depth = depth
        # ingest decode sharding (datax.job.process.ingest.*): the
        # conf'd shard count the native decoder fans each payload
        # across (designer knob jobDecoderThreads -> generation;
        # DATAX_DECODER_THREADS stays the operator override). None =
        # engine default (cap 4 — ingest shares the host with the
        # engine loop and sinks).
        ing_conf = process_conf.get_sub_dictionary("ingest.")
        decoder_threads = ing_conf.get_int_option("decoderthreads")
        if decoder_threads is not None and decoder_threads < 1:
            raise EngineException(
                f"process.ingest.decoderthreads must be >= 1, got "
                f"{decoder_threads}"
            )
        self.decoder_threads = decoder_threads
        self.sized_transfer = (
            (pipe_conf.get_or_else("sizedtransfer", "true") or "").lower()
            != "false"
        ) and self.mesh is None
        # per-output EWMA of observed valid row counts — the sized
        # transfer capacity tracks this, bucketed to powers of two
        self.transfer_ewma: Dict[str, float] = {}
        # counters drained into Transfer_<name>_Count metrics at collect
        self.transfer_stats: Dict[str, int] = {}
        # outputs still riding the post-overflow doubled headroom:
        # name -> batches remaining
        self.transfer_boost: Dict[str, int] = {}
        # donated double-buffered output slots (off under a mesh, whose
        # sharded outputs can't alias a single-device buffer):
        # (output, capacity) -> [slot A, slot B], each slot the
        # (TableData, landed-event of the batch that last shipped it)
        self.output_slots_enabled = (
            (pipe_conf.get_or_else("outputslots", "true") or "").lower()
            != "false"
        ) and self.mesh is None
        # observed mesh communication (datax.job.process.mesh.observe,
        # default on): under a mesh the compiled step's collective
        # census (dist/mesh.py collective_summary) exports per batch as
        # Mesh_ICI_Bytes / Mesh_Reshard_Count — the real runtime
        # counterpart the DX51x conformance ratios judge against the
        # embedded sharding model (process.mesh.model). The census
        # costs one extra lower+compile of the step (the persistent
        # compilation cache makes it a deserialize when configured).
        self.mesh_observe = (
            (
                process_conf.get_sub_dictionary("mesh.")
                .get_or_else("observe", "true") or ""
            ).lower() != "false"
        ) and self.mesh is not None
        # None = not yet censused; False = census failed (don't retry
        # every batch); else a dist.mesh.MeshCollectives
        self.mesh_collectives = None
        self._slots: Dict[Tuple[str, int], list] = {}
        self._slot_parity: Dict[str, int] = {}
        # serializes ring/state donation in dispatch against the
        # window-state snapshot a background landing thread may take at
        # checkpoint time (snapshotting a ring the next dispatch has
        # already donated would read a deleted buffer)
        self._device_state_lock = threading.Lock()

        # partitioned state (datax.job.process.state.*): every stateful
        # surface — accumulator tables AND window-ring snapshots —
        # hashes onto `partitions` key-range partitions
        # (runtime/statepartition.py); this replica owns the contiguous
        # range `replicaindex`/`replicacount` assigns it, persists only
        # those partitions, and (with `snapshoturl` set) ships them
        # through the shared objstore:// store so a successor replica
        # pulls exactly its assigned partitions on a rescale handoff.
        # `partitionkey` names the key column (per-table override:
        # statetable.<name>.partitionkey); `filteringest` drops rows of
        # un-owned partitions at encode time (key-routed ingest — the
        # Kafka key-partitioning contract restated for this engine).
        from .statepartition import (
            DEFAULT_STATE_PARTITIONS,
            ObjstoreSnapshotStore,
            owned_partitions,
        )

        state_conf = process_conf.get_sub_dictionary("state.")
        sp = state_conf.get_int_option("partitions")
        if sp is not None and sp < 1:
            raise EngineException(
                f"process.state.partitions must be >= 1, got {sp}"
            )
        self.state_partitions = sp or DEFAULT_STATE_PARTITIONS
        self.state_replica_count = max(
            1, state_conf.get_int_option("replicacount") or 1
        )
        self.state_replica_index = state_conf.get_int_option("replicaindex") or 1
        if not 1 <= self.state_replica_index <= self.state_replica_count:
            raise EngineException(
                f"process.state.replicaindex must be in "
                f"1..{self.state_replica_count}, got {self.state_replica_index}"
            )
        self.state_owned = owned_partitions(
            self.state_replica_index, self.state_replica_count,
            self.state_partitions,
        )
        self.state_partition_key = state_conf.get("partitionkey")
        self.state_filter_ingest = (
            (state_conf.get_or_else("filteringest", "false") or "")
            .lower() == "true"
        ) and self.state_replica_count > 1
        self._filter_warned: set = set()
        self.state_mirror = None
        snapshot_url = state_conf.get("snapshoturl")
        if snapshot_url:
            try:
                self.state_mirror = ObjstoreSnapshotStore(snapshot_url)
            except ValueError as e:
                raise EngineException(
                    f"process.state.snapshoturl invalid: {e}"
                ) from None
        # State_* metric deltas drained at collect + DX53x events the
        # host flight-records (shared with every StateTable)
        self.state_stats: Dict[str, float] = {}
        self.state_events: List[dict] = []

        # AOT compile + persistent compilation cache (the zero-cold-
        # start path, datax.job.process.compile.*): `manifest` carries
        # the compile manifest config generation embedded (inline JSON,
        # a file path, or objstore:// — analysis/compilecheck.py emits
        # it); with `aot` (default on when a manifest is present) every
        # manifest entry is compiled at INIT instead of first dispatch.
        # `cachedir`/`cacheurl` route XLA's persistent compilation
        # cache through a local dir / the shared object store so
        # restarts and preemption recovery deserialize instead of
        # recompiling. `jitcachecap` bounds the transfer-helper jit
        # caches (shared default with the DX601 lint).
        comp_conf = process_conf.get_sub_dictionary("compile.")
        cap_conf = comp_conf.get_int_option("jitcachecap")
        if cap_conf is not None:
            if cap_conf < 1:
                raise EngineException(
                    f"process.compile.jitcachecap must be >= 1, got "
                    f"{cap_conf}"
                )
            set_jit_cache_cap(cap_conf)
        self.compile_manifest: Optional[dict] = None
        manifest_raw = _read_maybe_file(comp_conf.get("manifest"))
        if manifest_raw:
            try:
                self.compile_manifest = json.loads(manifest_raw)
            except ValueError as e:
                logger.warning("compile manifest does not parse: %s", e)
        self.aot_enabled = (
            (comp_conf.get_or_else("aot", "true") or "").lower() != "false"
        ) and self.compile_manifest is not None
        self.compile_cache_dir = comp_conf.get("cachedir")
        self.compile_cache_url = comp_conf.get("cacheurl")
        # the persistent compilation cache arms for ANY processor that
        # configures it — AOT or not (LiveQuery kernels have no
        # manifest, but their per-query compiles still deserialize on
        # the next create/restart). The AOT warm reuses this instance
        # for its hit/miss accounting and objstore push.
        self._compile_cache = None
        if self.compile_cache_dir or self.compile_cache_url:
            try:
                from ..compile.aotcache import PersistentCompileCache

                self._compile_cache = PersistentCompileCache(
                    self.compile_cache_dir, self.compile_cache_url
                )
                self._compile_cache.enable()
            except Exception as e:  # noqa: BLE001 — cache is an optimization
                logger.warning("persistent compile cache unavailable: %s", e)
                self._compile_cache = None
        # Compile_* metric deltas drained at collect (ColdStart_Ms,
        # Cache_Hit_Count, Cache_Miss_Count, WarmMiss_Count)
        self.compile_stats: Dict[str, float] = {}
        self._aot_warmed = False
        # step jit-cache size right after the warm: growth past it at
        # dispatch time means a promised warm start was missed (DX604)
        self._warm_step_mark: Optional[int] = None

        self.interval_s = float(
            input_conf.get_or_else("streaming.intervalinseconds", "1")
        )
        max_rate = int(input_conf.get_or_else("eventhub.maxrate", "1000"))
        # flow-level default batch capacity: ctor arg > process conf
        # (generation.py S400 writes process.batchcapacity) > input conf
        default_capacity = (
            batch_capacity
            or process_conf.get_int_option("batchcapacity")
            or int(
                input_conf.get_or_else(
                    "streaming.maxbatchsize",
                    str(max(64, int(max_rate * self.interval_s))),
                )
            )
        )

        self.timestamp_column = process_conf.get("timestampcolumn")
        self.watermark_s = process_conf.get_duration_option("watermark") or 0.0

        # per-row Properties map (reference: handler/PropertiesHandler.scala
        # — appendproperty.* conf entries + BatchTime/InputTime/Partition/
        # CPTime/CPExecutor per row). Conf-gated: encoding per-batch
        # strings costs a dictionary entry per batch second, so flows opt
        # in by declaring appendproperty.* keys or
        # process.properties.enabled=true; otherwise the column stays
        # NULL. SystemProperties stays NULL — it carries AMQP transport
        # metadata the TCP/Kafka ingest paths do not have.
        self.append_properties = dict(
            process_conf.get_sub_dictionary("appendproperty.").dict
        )
        self.properties_enabled = bool(self.append_properties) or (
            process_conf.get_or_else("properties.enabled", "false") or ""
        ).lower() == "true"
        self._props_cache: Dict[Tuple, int] = {}
        import socket as _socket

        self._executor_id = f"{_socket.gethostname()}:{os.getpid()}"

        # planner capacities are flow conf, not constants: maxgroups
        # bounds GROUP BY fan-out, joincapacity bounds join output rows
        # (both surface overflow as metrics rather than failing)
        self.planner_config = self._planner_config(process_conf)

        # -- named sources ------------------------------------------------
        self.specs: Dict[str, SourceSpec] = {}
        source_groups = dict_.group_by_sub_namespace(
            SettingNamespace.JobPrefix + "input.sources."
        )
        global_projection = process_conf.get_string_seq_option("projection")
        if source_groups:
            # the flow's main input (input.default.*) joins the map as
            # the primary source when it is declared and the sources map
            # doesn't name its own "default" — the designer's model is
            # "main input + additional sources"
            if (
                DEFAULT_SOURCE not in source_groups
                and input_conf.get("blobschemafile")
            ):
                self.specs[DEFAULT_SOURCE] = self._make_spec(
                    DEFAULT_SOURCE, input_conf, default_capacity,
                    global_projection,
                )
            for sname, sub in source_groups.items():
                self.specs[sname] = self._make_spec(
                    sname, sub, default_capacity,
                    # the flow-level projection applies to the default
                    # source only; others declare their own
                    global_projection if sname == DEFAULT_SOURCE else None,
                )
        else:
            self.specs[DEFAULT_SOURCE] = self._make_spec(
                DEFAULT_SOURCE, input_conf, default_capacity, global_projection
            )
        targets = [s.target for s in self.specs.values()]
        if len(set(targets)) != len(targets):
            raise EngineException(
                f"input sources project into duplicate tables: {targets}"
            )

        # back-compat single-source surface: the primary spec
        self.primary = (
            DEFAULT_SOURCE if DEFAULT_SOURCE in self.specs
            else next(iter(self.specs))
        )
        primary = self.specs[self.primary]
        self.input_schema = primary.schema
        self.raw_schema = primary.raw_schema
        self.batch_capacity = primary.capacity

        # transform
        transform_text = _read_maybe_file(process_conf.get("transform")) or ""
        self.transform_text = transform_text

        # reference data
        self.refdata = load_reference_data_tables(dict_, self.dictionary)

        # time windows (handler/TimeWindowHandler.scala:23-68); each
        # window targets one projected table (conf `table`, else the
        # longest target that prefixes the window name, else the default)
        self.windows: Dict[str, Tuple[str, float]] = {}
        for wname, sub in dict_.group_by_sub_namespace(
            SettingNamespace.JobProcessPrefix + "timewindow."
        ).items():
            table = sub.get("table") or self._window_target(wname, targets)
            if table not in targets:
                raise EngineException(
                    f"timewindow {wname} targets unknown table {table!r} "
                    f"(projected tables: {targets})"
                )
            self.windows[wname] = (table, sub.get_duration("windowduration"))

        # state tables — partitioned: each replica persists only its
        # owned key-range partitions, mirrored through objstore:// when
        # process.state.snapshoturl is set (the rescale-handoff path)
        self.state_tables: Dict[str, StateTable] = {}
        for sname, sub in dict_.group_by_sub_namespace(
            SettingNamespace.JobProcessPrefix + "statetable."
        ).items():
            schema = parse_state_table_schema(sub.get_string("schema"))
            location = sub.get_or_else("location", f"/tmp/dxtpu-state/{sname}")
            key = sub.get("partitionkey") or (
                self.state_partition_key
                if self.state_partition_key in schema.types else None
            )
            self.state_tables[sname] = StateTable(
                sname, schema, self.batch_capacity * 4, location,
                partitions=self.state_partitions,
                owned=self.state_owned,
                partition_key=key,
                mirror=self.state_mirror,
                stats=self.state_stats,
                events=self.state_events,
            )

        # jit re-traces observed since the last collect (UDF-refresh
        # rebuilds + shape/dictionary-growth cache misses past the
        # initial trace) — drained into the Retrace_Count metric, the
        # conformance monitor's DX503 input. The mark is the jit cache
        # size already accounted for (None = initial trace still due).
        self.retrace_count = 0
        self._retrace_mark: Optional[int] = None

        self._build_pipeline(output_datasets)
        self._init_device_state()
        self._jit_step()
        if self.aot_enabled:
            self._aot_warm()

    # -- build -----------------------------------------------------------
    def _planner_config(self, process_conf: SettingDictionary) -> PlannerConfig:
        kwargs = {}
        maxgroups = (
            process_conf.get_int_option("maxgroups")
            or process_conf.get_int_option("groupcapacity")
        )
        if maxgroups is not None:
            if maxgroups < 1:
                raise EngineException(
                    f"process.maxgroups must be >= 1, got {maxgroups}"
                )
            kwargs["max_group_capacity"] = maxgroups
        joincap = process_conf.get_int_option("joincapacity")
        if joincap is not None:
            if joincap < 1:
                raise EngineException(
                    f"process.joincapacity must be >= 1, got {joincap}"
                )
            kwargs["join_capacity"] = joincap
        return PlannerConfig(**kwargs)

    def _make_spec(
        self,
        name: str,
        conf: SettingDictionary,
        default_capacity: int,
        global_projection: Optional[List[str]],
    ) -> SourceSpec:
        schema_text = _read_maybe_file(conf.get("blobschemafile"))
        if schema_text is None:
            raise ValueError(
                f"input schema (blobschemafile) is required for source {name!r}"
            )
        schema = Schema.from_spark_json(schema_text)

        capacity = (
            conf.get_int_option("streaming.maxbatchsize") or default_capacity
        )
        if self.mesh is not None:
            # row shards must divide evenly over the data axis
            n = self.mesh.size
            capacity = ((capacity + n - 1) // n) * n

        target = conf.get("target") or (
            DatasetName.DataStreamProjection if name == DEFAULT_SOURCE else name
        )

        raw_types = dict(schema_to_view(schema).types)
        raw_types.setdefault(ColumnName.RawPropertiesColumn, "string")
        raw_types.setdefault(ColumnName.RawSystemPropertiesColumn, "string")
        raw_schema = ViewSchema(raw_types)

        # projection: selectExpr lines (handler/ProjectionHandler.scala);
        # per-source `projection` conf wins, then the flow-level one for
        # the default source, then the normalization default
        projections = (
            conf.get_string_seq_option("projection") or global_projection or []
        )
        steps = [_read_maybe_file(p) for p in projections] or [
            self._default_projection(schema)
        ]
        return SourceSpec(
            name=name,
            target=target,
            schema=schema,
            raw_schema=raw_schema,
            projection_steps=steps,
            capacity=capacity,
            conf=conf,
        )

    @staticmethod
    def _window_target(wname: str, targets: List[str]) -> str:
        return window_target(wname, targets)

    def _default_projection(self, schema: Schema) -> str:
        return default_projection(schema, self.timestamp_column)

    def _projection_select(self, step_text: str, from_table: str):
        return projection_select(step_text, from_table)

    def _build_pipeline(self, output_datasets: Optional[List[str]]):
        pc = PipelineCompiler(
            self.dictionary, self.udfs, config=self.planner_config
        )
        # one dictionary-table registry for the whole flow (projection +
        # transform share string-op tables; see compile/stringops.py);
        # the builder materializes them per batch for the jitted step
        self.aux_registry = pc.aux

        # 1. per-source projection pipelines: Raw -> <target table>
        from ..compile.planner import SelectCompiler

        self.projection_views: Dict[str, List] = {}
        self.target_schemas: Dict[str, ViewSchema] = {}
        for spec in self.specs.values():
            proj_catalog = {
                "Raw": spec.raw_schema,
                DatasetName.DataStreamRaw: spec.raw_schema,
            }
            proj_caps = {
                "Raw": spec.capacity,
                DatasetName.DataStreamRaw: spec.capacity,
            }
            cur_name = "Raw"
            views = []
            for i, step_text in enumerate(spec.projection_steps):
                sel = self._projection_select(step_text, cur_name)
                compiler = SelectCompiler(
                    proj_catalog, proj_caps, self.dictionary, self.udfs,
                    self.planner_config, aux=pc.aux,
                )
                vname = (
                    spec.target
                    if i == len(spec.projection_steps) - 1
                    else f"__proj{i}"
                )
                view = compiler.compile_select(vname, sel)
                views.append(view)
                proj_catalog[vname] = view.schema
                proj_caps[vname] = view.capacity
                cur_name = vname
            self.projection_views[spec.name] = views
            self.target_schemas[spec.target] = proj_catalog[spec.target]
        self.projected_schema = self.target_schemas[
            self.specs[self.primary].target
        ]

        # 2. window slots per windowed target table
        self.ring_slots: Dict[str, int] = {}
        for wname, (table, dur_s) in self.windows.items():
            if self.timestamp_column not in self.target_schemas[table].types:
                raise EngineException(
                    f"timewindow {wname} requires timestamp column "
                    f"{self.timestamp_column!r} in table {table}"
                )
            slots = num_slots(dur_s, self.watermark_s, self.interval_s)
            self.ring_slots[table] = max(self.ring_slots.get(table, 1), slots)

        # 3. main pipeline inputs
        target_caps = {s.target: s.capacity for s in self.specs.values()}
        inputs: Dict[str, Tuple[ViewSchema, int]] = {
            t: (sch, target_caps[t]) for t, sch in self.target_schemas.items()
        }
        for wname, (table, _dur) in self.windows.items():
            inputs[wname] = (
                self.target_schemas[table],
                self.ring_slots[table] * target_caps[table],
            )
        for rname, (rschema, rtable) in self.refdata.items():
            inputs[rname] = (rschema, rtable.capacity)
        state_inputs = {
            sname: (st.schema, st.capacity) for sname, st in self.state_tables.items()
        }

        self.pipeline: Pipeline = pc.compile_transform(
            self.transform_text, inputs, state_inputs
        )
        from ..compile.stringops import AuxTableBuilder

        from ..compile.stringops import _MAX_ROUNDS

        try:
            max_rounds = self.dict.get_int_option(
                "datax.job.process.stringmap.maxrounds")
        except ValueError as e:
            raise EngineException(
                f"datax.job.process.stringmap.maxrounds must be an "
                f"integer: {e}"
            ) from None
        if max_rounds is None:
            max_rounds = _MAX_ROUNDS
        elif max_rounds < 1:
            raise EngineException(
                "datax.job.process.stringmap.maxrounds must be >= 1, got "
                f"{max_rounds}"
            )
        self.aux_tables = AuxTableBuilder(
            self.aux_registry, self.dictionary,
            max_rounds=max_rounds,
            strict=(self.dict.get_or_else(
                "datax.job.process.stringmap.strict", "false") or ""
            ).lower() == "true",
        )

        # output datasets: explicit list or conf-declared output names that
        # match pipeline views (S500-style dataset==output-name contract)
        if output_datasets is None:
            conf_outputs = self.dict.get_sub_dictionary(
                SettingNamespace.JobOutputPrefix
            ).group_by_sub_namespace()
            output_datasets = [
                n for n in conf_outputs if n in self.pipeline.catalog
            ]
        self.output_datasets = [
            n for n in output_datasets if n in self.pipeline.catalog
        ]

    def _init_device_state(self):
        # dx-race: single-threaded init/reset path — runs before the host
        # starts the landing worker (or with it quiesced on LQ reset)
        self.window_buffers: Dict[str, WindowBuffers] = {}
        target_caps = {s.target: s.capacity for s in self.specs.values()}
        for table, slots in self.ring_slots.items():
            self.window_buffers[table] = make_buffers(
                self.target_schemas[table], target_caps[table], slots
            )
        # state load is the handoff-critical path of a successor
        # replica (pull owned partitions from the mirror): time it once
        # so State_Handoff_Ms reports what the rescale actually cost
        t0 = time.time()
        self.state_data: Dict[str, TableData] = {
            sname: st.load(self.dictionary) for sname, st in self.state_tables.items()
        }
        if self.state_tables:
            self.state_stats.setdefault(
                "Handoff_Ms", (time.time() - t0) * 1000.0
            )
        self._slot_counter = 0
        self._base_ms: Optional[int] = None
        # host-side ingest counters (e.g. rows dropped for garbage
        # timestamps), drained into metrics at each collect
        self.ingest_stats: Dict[str, int] = {}
        # monotonic malformed-line total (never cleared — the host's
        # pilot reads per-poll deltas off it, so the collect-time drain
        # of ingest_stats can't race the flood signal)
        self.malformed_rows_total = 0
        self._native_decoders: Dict[str, object] = {}
        # ingest decode fast path state: per-source pools of persistent
        # 64-byte-aligned packed H2D matrices (decoder shards write
        # straight into them; slots release when their batch lands),
        # the schema-column -> matrix-row maps, and the decode gauges
        # (Decode_Shards / Decode_RowsPerSec / Decode_BufferReuse_Count)
        self._ingest_pools: Dict[str, object] = {}
        self._ingest_col_rows: Dict[str, List[int]] = {}
        self._decode_shards: Optional[int] = None
        self._decode_rows_per_sec: Optional[float] = None
        # which decode engine served the last encode_json_bytes call:
        # "native-sharded" (packed pool path) / "native-mt" (row-layout
        # native, e.g. under a mesh) / "python-fallback" — bench.py
        # records it in BENCH_CONTEXT and the regression gate refuses
        # cross-path comparisons
        self.last_decoder_path: Optional[str] = None

    def reset_state(self) -> None:
        """Zero device state (rings, slot counter, time base; state
        tables reload from their location). For re-entrant uses like
        LiveQuery kernels where each execute must be idempotent."""
        self._init_device_state()

    # -- window-state checkpoint ------------------------------------------
    def snapshot_window_state(self) -> Dict[str, object]:
        """Host copy of everything a restart would otherwise lose: the
        window ring buffers, the slot counter, the time base the ring
        timestamps are relative to, AND the string dictionary — ring
        columns hold dictionary ids, which only mean anything against
        the dictionary that encoded them. Numpy-only; feed to
        ``WindowStateCheckpointer.save`` (reference restores window state
        via the StreamingContext checkpoint, StreamingHost.scala:83-89)."""
        # under the device-state lock: the checkpoint may run on the
        # background landing thread while the dispatch thread is about
        # to donate these very ring buffers into the next step. The
        # copies must be REAL copies — ``np.asarray`` of a CPU jax
        # array is a zero-copy VIEW of the device buffer, and a view
        # escaping this lock dangles the moment the next dispatch
        # donates the ring (reads after that are use-after-free: heap
        # corruption, not just stale data)
        with self._device_state_lock:
            rings = {}
            for table, buf in self.window_buffers.items():
                rings[table] = {
                    "cols": {
                        c: np.array(a, copy=True)
                        for c, a in buf.cols.items()
                    },
                    "valid": np.array(buf.valid, copy=True),
                }
            return {
                "rings": rings,
                "slot_counter": self._slot_counter,
                "base_ms": self._base_ms,
                "dictionary": self.dictionary.entries(),
            }

    def restore_window_state(self, snap: Dict[str, object]) -> bool:
        """Restore a ``snapshot_window_state`` result. Shape-checked: a
        conf change that resized the rings invalidates the snapshot
        (returns False and keeps the fresh zero state). The saved
        dictionary must agree with the strings this process has already
        encoded (same conf => same compile-time literals in the same
        order); on agreement the remaining saved entries replay so every
        restored ring id decodes to the string it meant before the
        restart."""
        saved_dict = snap.get("dictionary")
        if saved_dict is not None:
            if not self.dictionary.restore_entries(saved_dict):
                return False
        rings = snap.get("rings", {})
        restored: Dict[str, WindowBuffers] = {}
        for table, buf in self.window_buffers.items():
            saved = rings.get(table)
            if saved is None:
                return False
            if set(saved["cols"]) != set(buf.cols) or any(
                saved["cols"][c].shape != buf.cols[c].shape
                # dx-race: allow-zero-copy dtype probe only — no element read
                or saved["cols"][c].dtype != np.asarray(buf.cols[c]).dtype
                for c in buf.cols
            ):
                return False
            # copy=True is load-bearing: ``jnp.asarray`` ZERO-COPIES a
            # 64-byte-aligned numpy buffer on the CPU backend, and the
            # rings are the step's DONATED argument — donating an
            # aliased buffer has XLA free memory numpy owns (heap
            # corruption, flaky segfaults under the pipelined loop)
            restored[table] = WindowBuffers(
                {c: jnp.array(a, copy=True)
                 for c, a in saved["cols"].items()},
                jnp.array(saved["valid"], copy=True),
            )
        if self.mesh is not None:
            from ..dist.mesh import ring_sharding

            sh = ring_sharding(self.mesh)
            restored = {
                t: WindowBuffers(
                    {c: jax.device_put(a, sh) for c, a in b.cols.items()},
                    jax.device_put(b.valid, sh),
                )
                for t, b in restored.items()
            }
        # publish atomically under the device-state lock: a checkpoint on
        # the landing thread must never see half-swapped ring state
        with self._device_state_lock:
            self.window_buffers = restored
            self._slot_counter = int(snap.get("slot_counter", 0))
            base = snap.get("base_ms")
            self._base_ms = int(base) if base is not None else None
        return True

    # -- partitioned window state (the rescale-handoff path) --------------
    WINDOW_STORE_NAME = "__window__"

    def _window_key_cols(self) -> Dict[str, Tuple[str, str]]:
        """Ring table -> (partition-key column, kind): the conf'd
        ``state.partitionkey`` when the table carries it, else the
        first non-timestamp column (rows of tables with no usable key
        land in partition 0 — statepartition.split_window_snapshot)."""
        out: Dict[str, Tuple[str, str]] = {}
        for table in self.ring_slots:
            types = self.target_schemas[table].types
            key = None
            if self.state_partition_key and \
                    self.state_partition_key in types:
                key = self.state_partition_key
            else:
                key = next(
                    (c for c in types if c != self.timestamp_column), None
                )
            if key is not None:
                out[table] = (key, types[key])
        return out

    def push_window_partitions(self, snap: Dict[str, object]) -> int:
        """Ship this replica's OWNED window partitions to the objstore
        mirror as per-partition A/B snapshots + pointer (the same
        layout the state tables use). Called on the checkpoint cadence
        after commit; fail-closed like every state push."""
        if self.state_mirror is None or not snap.get("rings"):
            return 0
        from .statepartition import other_side, snapshot_to_bytes
        from .statepartition import split_window_snapshot

        parts = split_window_snapshot(
            snap, self.state_partitions, self._window_key_cols(),
            dictionary=self.dictionary, only=self.state_owned,
        )
        for p, part_snap in parts.items():
            prefix = f"{self.WINDOW_STORE_NAME}/p{p:02d}"
            side = other_side(self.state_mirror.get_pointer(prefix) or "B")
            self.state_mirror.put_files(
                prefix, side, {"window.npz": snapshot_to_bytes(part_snap)}
            )
            self.state_mirror.put_pointer(prefix, side)
        self.state_stats["Snapshot_Push_Count"] = (
            self.state_stats.get("Snapshot_Push_Count", 0) + len(parts)
        )
        return len(parts)

    def pull_window_partitions(self) -> List[Dict]:
        """Fetch this replica's assigned window partitions from the
        mirror — possibly written by SEVERAL predecessors. A corrupt
        active side falls back to the standby (DX530 +
        ``State_LoadFallback_Count``), both-bad loads nothing for that
        partition (DX531) and the un-acked window replay re-aggregates."""
        if self.state_mirror is None:
            return []
        from .statepartition import other_side, snapshot_from_bytes

        out: List[Dict] = []
        pulled = 0
        for p in self.state_owned:
            prefix = f"{self.WINDOW_STORE_NAME}/p{p:02d}"
            pointer = self.state_mirror.get_pointer(prefix)
            if pointer is None:
                continue
            snap = None
            for attempt, side in enumerate((pointer, other_side(pointer))):
                data = self.state_mirror.get_file(prefix, side, "window.npz")
                if data is None:
                    continue
                try:
                    snap = snapshot_from_bytes(data)
                    break
                except Exception as e:  # noqa: BLE001 — corrupt snapshot
                    self.state_stats["LoadFallback_Count"] = (
                        self.state_stats.get("LoadFallback_Count", 0) + 1
                    )
                    code = "DX530" if attempt == 0 else "DX531"
                    self.state_events.append({
                        "code": code, "table": self.WINDOW_STORE_NAME,
                        "partition": p, "side": side,
                        "message": (
                            f"window partition {p} side {side} "
                            f"unreadable ({e})"
                        ),
                        "ts": time.time(),
                    })
            if snap is not None:
                out.append(snap)
                pulled += 1
        if pulled:
            self.state_stats["Snapshot_Pull_Count"] = (
                self.state_stats.get("Snapshot_Pull_Count", 0) + pulled
            )
        return out

    def restore_window_partitions(self) -> bool:
        """The successor half of a window handoff: pull the assigned
        partitions, merge them (re-packed per slot, timestamps rebased,
        string ids remapped into the LIVE dictionary —
        statepartition.merge_window_snapshots) and restore. False when
        the mirror holds nothing usable."""
        parts = self.pull_window_partitions()
        if not parts:
            return False
        from .statepartition import merge_window_snapshots

        merged = merge_window_snapshots(
            parts,
            {t: dict(self.target_schemas[t].types) for t in self.ring_slots},
            self.dictionary,
            self.timestamp_column,
        )
        if merged is None:
            return False
        dropped = merged.pop("dropped_rows", 0)
        if dropped:
            self.state_stats["WindowRows_Dropped_Count"] = (
                self.state_stats.get("WindowRows_Dropped_Count", 0) + dropped
            )
        return self.restore_window_state(merged)

    # -- the jitted step --------------------------------------------------
    def _jit_step(self):
        step = build_step_fn(
            ts_col=self.timestamp_column,
            windows=dict(self.windows),
            output_datasets=list(self.output_datasets),
            state_names=list(self.state_tables),
            refdata_names=list(self.refdata),
            ring_tables=list(self.ring_slots),
            pipeline=self.pipeline,
            source_targets=[(s.name, s.target) for s in self.specs.values()],
            proj_views=dict(self.projection_views),
            primary_target=self.specs[self.primary].target,
        )
        self._step_fn = step
        # donate the rings: the old buffers are dead after the step, so
        # XLA updates the (large) window rings in place instead of
        # allocating copies each batch. State tables are NOT donated — a
        # pipelined PendingBatch still reads its state for the A/B
        # overwrite after the next batch has been dispatched.
        if self.mesh is not None:
            from ..dist.mesh import step_shardings

            in_shardings, out_shardings = step_shardings(self.mesh)
            self._step = jax.jit(
                step,
                in_shardings=in_shardings,
                out_shardings=out_shardings,
                donate_argnums=STEP_DONATE_ARGNUMS,
            )
        else:
            self._step = jax.jit(step, donate_argnums=STEP_DONATE_ARGNUMS)

    # -- per-batch host path ----------------------------------------------
    def _spec(self, source: Optional[str]) -> SourceSpec:
        return self.specs[source or self.primary]

    def _properties_id(self, base_ms: int, file_info: Optional[dict] = None) -> int:
        """Dictionary id of the per-row Properties JSON map (reference:
        PropertiesHandler's per-row UDF result). Cached per (batch
        second, file) so repeated rows share one dictionary entry."""
        import datetime as _dt

        key = (base_ms, file_info.get("path") if file_info else None)
        sid = self._props_cache.get(key)
        if sid is not None:
            return sid

        def iso(ms: int) -> str:
            return _dt.datetime.fromtimestamp(
                ms / 1000, _dt.timezone.utc
            ).strftime("%Y-%m-%d %H:%M:%S")

        from ..constants import ProcessingPropertyName as P

        props = dict(self.append_properties)
        props[P.BatchTime] = iso(base_ms)
        props[P.CPTime] = iso(int(time.time()) * 1000)
        props[P.CPExecutor] = self._executor_id
        if file_info:
            if file_info.get("fileTimeMs"):
                props[P.BlobTime] = iso(int(file_info["fileTimeMs"]))
            if file_info.get("path"):
                props[P.BlobPathHint] = os.path.basename(file_info["path"])
        sid = self.dictionary.encode(json.dumps(props, sort_keys=True))
        if len(self._props_cache) > 4096:
            self._props_cache.clear()
        self._props_cache[key] = sid
        return sid

    def encode_rows(
        self, rows: List[dict], base_ms: int, source: Optional[str] = None
    ) -> TableData:
        """Host-side fallback encoder (python loop). The C++ decoder in
        native/ covers the hot path; benchmarks use the vectorized
        generator."""
        from ..core.batch import batch_from_rows

        spec = self._spec(source)
        b = batch_from_rows(
            rows, spec.schema, spec.capacity, self.dictionary,
            base_ms, stats=self.ingest_stats,
        )
        cols = dict(b.columns)
        if self.properties_enabled:
            default_id = self._properties_id(base_ms)
            props = np.full(spec.capacity, 0, np.int32)
            for i in range(min(len(rows), spec.capacity)):
                fi = rows[i].get(ColumnName.InternalColumnFileInfo)
                props[i] = (
                    self._properties_id(base_ms, fi) if fi else default_id
                )
            cols[ColumnName.RawPropertiesColumn] = jnp.asarray(props)
        cols.setdefault(
            ColumnName.RawPropertiesColumn,
            jnp.zeros((spec.capacity,), jnp.int32),
        )
        cols.setdefault(
            ColumnName.RawSystemPropertiesColumn,
            jnp.zeros((spec.capacity,), jnp.int32),
        )
        valid = b.valid
        if self.state_filter_ingest:
            key = self.state_partition_key
            src = cols.get(key)
            valid = jnp.asarray(self._filter_unowned(
                np.asarray(src) if src is not None else None,
                np.asarray(valid), spec,
            ))
        return TableData(cols, valid)

    def encode_json_bytes(
        self,
        data: bytes,
        base_ms: int,
        source: Optional[str] = None,
        packed: Optional[bool] = None,
        to_device: bool = True,
        fmt: str = "jsonl",
    ) -> Union[TableData, "PackedRaw"]:
        """Native ingest hot path: raw wire bytes decoded by the C++
        decoder (native/decoder.cpp) straight into columnar buffers —
        the from_json role at CommonProcessorFactory.scala:90-103
        without any per-event Python objects. Falls back to the Python
        row encoder if the native library is unavailable.

        ``fmt``: ``"jsonl"`` (newline-delimited JSON — socket/file
        sources) or ``"kafka-v2"`` (whole Kafka message-format-v2
        record batches from ``KafkaSource.poll_raw`` — the native
        walker verifies CRC-32C per batch, skips+counts corrupt
        batches, rejects compressed ones with a typed error, and feeds
        record values to the JSON column decoder in the same call).

        ``packed`` (default: auto — on for single-chip, off under a
        mesh, whose row shardings expect [capacity] leaves): decoder
        shards write directly into a persistent 64-byte-aligned pooled
        matrix in the single-transfer PackedRaw layout — zero per-row
        Python objects, zero per-call column allocations, no pack
        copy. The matrix is reused only after its batch lands
        (PendingBatch releases the slot), double-buffering the pool
        against the pipelined in-flight window."""
        from ..native import native_available

        spec = self._spec(source)
        if packed is None:
            packed = self.mesh is None
        if not native_available():
            self.last_decoder_path = "python-fallback"
            return self._encode_json_python(data, base_ms, spec, fmt)

        decoder = self._native_decoders.get(spec.name)
        if decoder is None:
            from ..native import NativeDecoder

            decoder = NativeDecoder(
                spec.schema, self.dictionary, threads=self.decoder_threads
            )
            self._native_decoders[spec.name] = decoder

        if packed:
            return self._encode_packed_native(
                decoder, data, base_ms, spec, fmt, to_device
            )

        # row-layout native path (mesh shardings want [capacity] leaves)
        self.last_decoder_path = "native-mt"
        if fmt == "kafka-v2":
            data = self._kafka_values_to_lines(data)
        arrays, valid, rows, _consumed = decoder.decode(data, spec.capacity)
        self._decode_shards = decoder.last_shards
        self._count_jsonl_malformed(data, _consumed, rows)
        if decoder.last_bad_timestamps:
            self.ingest_stats["bad_timestamps"] = (
                self.ingest_stats.get("bad_timestamps", 0)
                + decoder.last_bad_timestamps
            )
        cap = spec.capacity
        np_cols: Dict[str, np.ndarray] = {}
        for col in spec.schema.columns:
            a = arrays[col.name]
            if col.ctype == ColType.TIMESTAMP:
                # slots the decoder left at 0 (field missing) stay at
                # relative 0; deltas saturate at the int32 range like the
                # Python encoder (core/batch.py) instead of wrapping
                a = np.where(
                    a == 0,
                    np.int64(0),
                    np.clip(a - np.int64(base_ms), -2**31, 2**31 - 1),
                ).astype(np.int32)
            elif col.ctype == ColType.BOOLEAN:
                a = a.astype(np.bool_)
            np_cols[col.name] = a
        for extra in (
            ColumnName.RawPropertiesColumn,
            ColumnName.RawSystemPropertiesColumn,
        ):
            if extra in spec.raw_schema.types and extra not in np_cols:
                if (
                    extra == ColumnName.RawPropertiesColumn
                    and self.properties_enabled
                ):
                    np_cols[extra] = np.full(
                        cap, self._properties_id(base_ms), np.int32
                    )
                else:
                    np_cols[extra] = np.zeros(cap, np.int32)
        valid = np.asarray(valid)
        if self.state_filter_ingest:
            valid = self._filter_unowned(
                np_cols.get(self.state_partition_key), valid, spec
            )
        return TableData(
            {c: jnp.asarray(a) for c, a in np_cols.items()},
            jnp.asarray(valid),
        )

    # -- ingest fast-path helpers -----------------------------------------
    def _count_jsonl_malformed(self, data: bytes, consumed: int,
                               rows: int) -> None:
        """Malformed lines in the consumed range = newline count minus
        decoded rows (the decoder zero-gaps them); feeds the
        Input_malformed_rows_Count metric and the pilot flood signal.
        Allocation-free line count (bytes.count is C): blank lines are
        rare enough that miscounting one as malformed can't move the
        pilot's 30% flood threshold."""
        consumed_blob = data[:consumed] if consumed else data
        lines_seen = consumed_blob.count(b"\n")
        if consumed_blob and not consumed_blob.endswith(b"\n"):
            lines_seen += 1
        malformed = max(0, lines_seen - int(rows))
        if malformed:
            self.ingest_stats["malformed_rows"] = (
                self.ingest_stats.get("malformed_rows", 0) + malformed
            )
            self.malformed_rows_total += malformed

    def _count_ingest(self, key: str, n: int, malformed: bool = False) -> None:
        if not n:
            return
        self.ingest_stats[key] = self.ingest_stats.get(key, 0) + n
        if malformed:
            self.malformed_rows_total += n

    def _kafka_values_to_lines(self, data: bytes) -> bytes:
        """Python record-batch walk for the row-layout/fallback paths:
        extract record values (CRC verified, corrupt batches counted,
        compressed rejected typed) and hand them to the line decoder.
        Well-formed JSON never contains a raw newline, so the join is
        loss-free; a malformed value containing one just counts as
        malformed twice."""
        from .kafka_wire import decode_record_batches

        stats: Dict[str, int] = {}
        recs, _next = decode_record_batches(data, stats=stats)
        self._count_ingest("CorruptBatch", stats.get("corrupt_batches", 0))
        return b"\n".join(v for _o, _ts, v in recs) + (b"\n" if recs else b"")

    def _encode_json_python(
        self, data: bytes, base_ms: int, spec: SourceSpec, fmt: str,
    ) -> TableData:
        """No native library: per-row Python decode (json.loads into the
        row encoder), with the same malformed/corrupt accounting as the
        fast path so the pilot's flood signal never goes blind."""
        import json as _json

        if fmt == "kafka-v2":
            from .kafka_wire import decode_record_batches

            stats: Dict[str, int] = {}
            recs, _next = decode_record_batches(data, stats=stats)
            self._count_ingest(
                "CorruptBatch", stats.get("corrupt_batches", 0)
            )
            lines: List[bytes] = [v for _o, _ts, v in recs]
        else:
            lines = data.splitlines()
        rows = []
        malformed = 0
        for ln in lines:
            if not ln.strip():
                # a blank jsonl line is framing noise; an EMPTY Kafka
                # record value is a real record with no event — count
                # it malformed like the native walker does
                if fmt == "kafka-v2":
                    malformed += 1
                continue
            try:
                rows.append(_json.loads(ln))
            except ValueError:
                malformed += 1  # skip malformed lines, but count
                continue        # them: the pilot's flood signal
            if len(rows) >= spec.capacity:
                break
        self._count_ingest("malformed_rows", malformed, malformed=True)
        return self.encode_rows(rows, base_ms, source=spec.name)

    def _encode_packed_native(
        self, decoder, data: bytes, base_ms: int, spec: SourceSpec,
        fmt: str, to_device: bool,
    ) -> "PackedRaw":
        """The allocation-free hot path: acquire a pooled, persistent,
        64-byte-aligned matrix already laid out as the packed H2D
        transfer and let the decoder shards write straight into it.
        The returned PackedRaw carries its pool slot; dispatch hands it
        to the PendingBatch, which releases it when the batch lands (or
        abandons) — never while the device step may still be reading
        the zero-copied buffer."""
        from ..native import PackedBufferPool

        layout = packed_raw_layout(spec.raw_schema.types)
        names = [c for c, _k in layout]
        n_rows = len(layout) + 1
        cap = spec.capacity
        pool = self._ingest_pools.get(spec.name)
        if (
            pool is None or pool.n_rows != n_rows or pool.capacity != cap
        ):
            pool = PackedBufferPool(n_rows, cap)
            # armed debug.buffersanitizer: released slots get poisoned
            pool.sanitizer = self.buffer_sanitizer
            self._ingest_pools[spec.name] = pool
        col_rows = self._ingest_col_rows.get(spec.name)
        if col_rows is None:
            index = {c: i for i, c in enumerate(names)}
            col_rows = [index[c.name] for c in spec.schema.columns]
            self._ingest_col_rows[spec.name] = col_rows
        valid_row = len(layout)
        mat = pool.acquire()
        t0 = time.perf_counter()
        try:
            if fmt == "kafka-v2":
                rows, kstats = decoder.decode_kafka_packed(
                    data, mat, col_rows, valid_row, base_ms, max_rows=cap
                )
                self._count_ingest(
                    "malformed_rows", kstats["malformed"], malformed=True
                )
                self._count_ingest("CorruptBatch", kstats["corrupt_batches"])
                # records that arrived without a row slot are LOST data
                # (a producer batch larger than the flow capacity) —
                # loud, never silent
                self._count_ingest(
                    "kafka_overflow_rows", kstats["overflow_dropped"]
                )
            else:
                rows, consumed = decoder.decode_packed(
                    data, mat, col_rows, valid_row, base_ms, max_rows=cap
                )
                self._count_jsonl_malformed(data, consumed, rows)
        except Exception:
            pool.release(mat)
            raise
        dt = time.perf_counter() - t0
        self.last_decoder_path = "native-sharded"
        self._decode_shards = decoder.last_shards
        if dt > 0 and rows:
            self._decode_rows_per_sec = rows / dt
        if decoder.last_bad_timestamps:
            self.ingest_stats["bad_timestamps"] = (
                self.ingest_stats.get("bad_timestamps", 0)
                + decoder.last_bad_timestamps
            )
        # rows the decoder doesn't own (Properties/SystemProperties):
        # the pool hands back dirty matrices, so (re)fill them per call
        # — one vectorized fill per extra row, not a fresh allocation
        schema_rows = set(col_rows)
        for i, cname in enumerate(names):
            if i in schema_rows:
                continue
            if (
                cname == ColumnName.RawPropertiesColumn
                and self.properties_enabled
            ):
                mat[i].fill(self._properties_id(base_ms))
            else:
                mat[i].fill(0)
        if self.state_filter_ingest:
            key = self.state_partition_key
            kv = None
            if key in names:
                krow = mat[names.index(key)]
                kind = dict(layout).get(key)
                kv = krow.view(np.float32) if kind == "f32" else krow
            new_valid = self._filter_unowned(
                kv, mat[valid_row] != 0, spec
            )
            mat[valid_row] = new_valid.astype(np.int32)
        pr = pack_from_matrix(mat, layout, to_device=to_device)
        # dx-race: owner-handoff pool slot rides the PackedRaw into the
        # PendingBatch, which releases it on land/abandon
        pr._ingest_pool = (pool, mat)
        return pr

    def encode_columns(
        self, np_cols: Dict[str, np.ndarray], n: int,
        source: Optional[str] = None,
    ) -> TableData:
        spec = self._spec(source)
        cap = spec.capacity
        fill_dtype = {"double": jnp.float32, "boolean": jnp.bool_}
        cols = {}
        for c, t in spec.raw_schema.types.items():
            if c in np_cols:
                a = np_cols[c]
                pad = np.zeros(cap, dtype=a.dtype)
                pad[: min(n, cap)] = a[: min(n, cap)]
                cols[c] = jnp.asarray(pad)
            elif (
                c == ColumnName.RawPropertiesColumn and self.properties_enabled
            ):
                cols[c] = jnp.full(
                    (cap,),
                    self._properties_id(int(time.time()) * 1000),
                    jnp.int32,
                )
            else:
                cols[c] = jnp.zeros((cap,), fill_dtype.get(t, jnp.int32))
        valid = np.zeros(cap, dtype=bool)
        valid[: min(n, cap)] = True
        if self.state_filter_ingest and n > 0:
            key = self.state_partition_key
            src = cols.get(key)
            valid = self._filter_unowned(
                np.asarray(src) if src is not None else None, valid, spec
            )
        return TableData(cols, jnp.asarray(valid))

    def _empty_raw(self, spec: SourceSpec) -> TableData:
        return self.encode_columns({}, 0, source=spec.name)

    def _filter_unowned(self, key_vals, valid: np.ndarray,
                        spec: SourceSpec) -> np.ndarray:
        """Key-routed ingest (``process.state.filteringest``): zero the
        validity of rows whose key hashes to a partition this replica
        does NOT own, so N replicas fed the same stream process each
        key exactly once between them (the consumer-group contract
        restated over key-range partitions). Dropped rows count into
        ``State_IngestFiltered_Count``. No-op unless armed AND the
        source's raw schema carries the conf'd partition key."""
        key = self.state_partition_key
        if not key or key not in spec.raw_schema.types:
            if spec.name not in self._filter_warned:
                self._filter_warned.add(spec.name)
                logger.warning(
                    "state.filteringest armed but source %r has no "
                    "partition-key column %r; NOT filtering",
                    spec.name, key,
                )
            return valid
        if key_vals is None:
            return valid
        from .statepartition import partition_ids

        pids = partition_ids(
            np.asarray(key_vals), self.state_partitions,
            spec.raw_schema.types[key], dictionary=self.dictionary,
        )
        mask = np.isin(pids, np.asarray(self.state_owned, dtype=np.int64))
        valid = np.asarray(valid)
        dropped = int(np.count_nonzero(valid & ~mask))
        if dropped:
            self.state_stats["IngestFiltered_Count"] = (
                self.state_stats.get("IngestFiltered_Count", 0) + dropped
            )
        return valid & mask

    def _debug_guard(self):
        """Context armed by the ``process.debug`` conf block around the
        jitted step: ``jax.debug_nans`` re-runs de-optimized on the
        first NaN and names the producing primitive; tracer-leak
        checking raises when user code lets a tracer escape the traced
        step. Both sanitize UDF-bearing test jobs — off (a no-op stack)
        in production confs."""
        import contextlib

        stack = contextlib.ExitStack()
        if self.debug_nans:
            stack.enter_context(jax.debug_nans(True))
        if self.debug_tracer_leaks:
            stack.enter_context(jax.checking_leaks())
        return stack

    def dispatch_batch(
        self,
        raw: Union[TableData, Dict[str, TableData]],
        batch_time_ms: Optional[int] = None,
    ) -> "PendingBatch":
        """Queue one micro-batch on the device and return a handle.

        ``raw``: one TableData (routed to the primary source) or a dict
        {source name -> TableData}; sources absent from the dict run with
        an empty batch, so independent streams may tick at their own pace.

        The device runs asynchronously: the caller can encode/dispatch
        the next batch (or run sinks for the previous one) while this
        batch computes — the P6 fetch/process overlap, done with the
        device stream instead of Spark's receiver threads. Collect the
        results with ``PendingBatch.collect()``.
        """
        t0 = time.time()
        if batch_time_ms is None:
            batch_time_ms = int(time.time() * 1000)
        if isinstance(raw, (TableData, PackedRaw)):
            raw = {self.primary: raw}
        for name in raw:
            if name not in self.specs:
                raise EngineException(
                    f"dispatch_batch got unknown source {name!r} "
                    f"(declared: {list(self.specs)})"
                )
        raw = {
            name: raw.get(name) or self._empty_raw(spec)
            for name, spec in self.specs.items()
        }
        # per-interval UDF refresh hooks; state changes re-trace the step
        # (CommonProcessorFactory.scala:351-353 onInterval invocation).
        # A throwing hook skips its refresh (previous trace keeps
        # serving) and surfaces as the UdfRefreshError metric rather
        # than killing the batch loop.
        from ..udf import UdfRegistry

        registry = UdfRegistry(self.udfs)
        if registry.refresh(batch_time_ms):
            self._build_pipeline(self.output_datasets)
            self._jit_step()  # the old jit closed over the old pipeline
            # the rebuild discards the compiled step: the re-trace the
            # next dispatch pays is real work the steady-state model
            # does not include
            self.retrace_count += 1
            self._retrace_mark = None
        if registry.last_errors:
            self.udf_refresh_errors += len(registry.last_errors)
        # whole-second base so device absolute-time math is exact
        new_base_ms = (batch_time_ms // 1000) * 1000
        if self._base_ms is None:
            with self._device_state_lock:
                self._base_ms = new_base_ms
        delta_ms = new_base_ms - self._base_ms
        if abs(delta_ms) > 2**31 - 1:
            # a restored checkpoint (or clock jump) more than ~24.8 days
            # out: every ring row is long past any window horizon, and
            # the int32 rebase would overflow — start from clean rings.
            # Published under the device-state lock so a checkpoint on
            # the landing thread never snapshots mid-swap rings.
            target_caps = {s.target: s.capacity for s in self.specs.values()}
            with self._device_state_lock:
                self.window_buffers = {
                    table: make_buffers(
                        self.target_schemas[table], target_caps[table], slots
                    )
                    for table, slots in self.ring_slots.items()
                }
            delta_ms = 0
        # the landing thread's checkpoint reads base/counter under this
        # lock; writes pair with it so a snapshot is never torn
        with self._device_state_lock:
            self._base_ms = new_base_ms
            counter = jnp.asarray(self._slot_counter, jnp.int32)
            self._slot_counter += 1

        base_s = jnp.asarray(new_base_ms // 1000, jnp.int32)
        now_rel_ms = jnp.asarray(batch_time_ms - new_base_ms, jnp.int32)

        refdata_tables = {n: t for n, (_, t) in self.refdata.items()}
        # string-op dictionary tables: refreshed AFTER this batch's encode
        # (so they cover every id the batch can contain), cached until the
        # dictionary grows; growth past table capacity retraces the step
        aux = self.aux_tables.tables()
        # pooled ingest buffers riding this batch's raw inputs: owned by
        # the PendingBatch until its landing (or abandon) — the step
        # zero-copies them on the CPU backend, so early reuse would be
        # a read of freed-for-overwrite memory
        ingest_buffers = [
            r._ingest_pool for r in raw.values()
            if getattr(r, "_ingest_pool", None) is not None
        ]
        # child span of the host's "dispatch" when a batch trace is
        # active (obs/tracing.py); a no-op under bench/LiveQuery drivers
        try:
            with _trace_span("device-enqueue"), self._debug_guard(), \
                    self._device_state_lock:
                out_datasets, new_rings, new_state, counts_vec = self._step(
                    raw, self.window_buffers, self.state_data, refdata_tables,
                    base_s, now_rel_ms, counter,
                    jnp.asarray(delta_ms, jnp.int32),
                    aux,
                )
                # carry device state forward without materializing — the
                # next dispatch may consume these handles before this
                # batch collects
                self.window_buffers = new_rings
                self.state_data = new_state
        except Exception:
            # the step never launched: the pool slots are safe to reuse
            for pool, mat in ingest_buffers:
                pool.release(mat)
            raise
        # sized output transfer: shrink each output's D2H copy to its
        # adaptive capacity (power-of-two bucket over the count EWMA),
        # written into the output's donated A/B transfer slot so the
        # buffers the background copies stream from stay resident.
        # The device has already compacted valid rows to the front, so
        # the slice keeps every real row as long as the cap holds; the
        # full-capacity table stays referenced for the two-phase
        # overflow fallback in collect().
        fetch_tables: Dict[str, TableData] = {}
        fetch_caps: Dict[str, int] = {}
        staged_slots = []  # (slot key, parity) filled below the handle
        for n, t in out_datasets.items():
            full_cap = int(t.valid.shape[0])
            cap = self.transfer_capacity(n, full_cap)
            fetch_caps[n] = cap
            fetch_tables[n] = self._stage_output(n, t, cap, full_cap,
                                                 staged_slots)
        handle = PendingBatch(
            self, self.pipeline, out_datasets, new_state, counts_vec,
            batch_time_ms, new_base_ms, t0,
            out_names=list(self.output_datasets),
            target_names=[s.target for s in self.specs.values()],
            fetch_tables=fetch_tables,
            fetch_caps=fetch_caps,
        )
        # this batch's pooled ingest matrices: released by the handle
        # when the batch lands/abandons, never before the step is done
        # dx-race: owner-handoff pool slots ride the PendingBatch; its
        # collect/abandon path is the unique releaser
        handle._ingest_buffers = ingest_buffers
        # each staged slot is owned by THIS batch until its transfer
        # lands: record the handle's landed-event so the dispatch that
        # next rotates onto the slot knows whether donation is safe
        for key, parity in staged_slots:
            table, _ev = self._slots[key][parity]
            # dx-race: owner-handoff slot ownership moves to this handle;
            # _stage_output checks the landed event before re-donating
            self._slots[key][parity] = (table, handle._landed)
        # begin the device->host result copies NOW (async enqueue, free):
        # by the time collect() runs — typically one pipelined iteration
        # later — the data has already crossed the boundary, so collect
        # pays no synchronous transport round trip. On split hosts that
        # round trip is a network RTT, the single largest per-batch cost.
        handle.start_fetch()
        return handle

    def _stage_output(
        self, name: str, t: TableData, cap: int, full_cap: int,
        staged_slots: list,
    ) -> TableData:
        """Build output ``name``'s transfer view at capacity ``cap``.

        With output slots enabled the view is written into one of the
        output's two resident transfer slots (A/B rotation): the slot
        buffer is DONATED into the jitted pack, so XLA writes the sliced
        rows straight into the transfer-ready memory the background D2H
        copy will stream from — batch N+1 packs into the other slot, so
        an in-flight transfer of batch N is never clobbered. A slot
        whose previous transfer has not landed yet (deep backlog, or an
        abandoned handle) falls back to a fresh buffer instead of
        blocking the dispatch loop — correctness first, reuse when safe.
        """
        if not self.output_slots_enabled or not all(
            v.shape[:1] == t.valid.shape for v in t.cols.values()
        ):
            return _slice_table(t, cap) if cap < full_cap else t
        key = (name, cap)
        ring = self._slots.setdefault(key, [None] * OUTPUT_SLOT_BUFFERS)
        parity = self._slot_parity.get(name, 0) % OUTPUT_SLOT_BUFFERS
        self._slot_parity[name] = parity + 1
        prev = ring[parity]
        if prev is not None and prev[1].is_set():
            # the batch that last shipped this slot has landed its host
            # copy: donate the buffers back into the pack
            staged = _pack_slot(t, prev[0], cap)
        else:
            # first use of this (output, cap) slot, or its transfer is
            # still in flight: allocate fresh transfer buffers
            if prev is not None:
                self._bump_transfer_stat("SlotContended")
            staged = _slice_table(t, cap)
        ring[parity] = (staged, _SET_EVENT)
        staged_slots.append((key, parity))
        return staged

    def process_batch(
        self,
        raw: Union[TableData, Dict[str, TableData]],
        batch_time_ms: Optional[int] = None,
    ) -> Tuple[Dict[str, List[dict]], Dict[str, float]]:
        """Run one micro-batch; returns (materialized datasets, metrics).

        reference: processDataset (CommonProcessorFactory.scala:333-399)
        incl. the metric names it emits (:344-379).
        """
        return self.dispatch_batch(raw, batch_time_ms).collect()

    # -- sized output transfer --------------------------------------------
    def transfer_capacity(self, name: str, full_cap: int) -> int:
        """Adaptive D2H transfer capacity for output ``name``: the EWMA
        of observed valid counts with ``TRANSFER_HEADROOM`` x burst
        margin (doubled for ``OVERFLOW_BOOST_BATCHES`` batches after an
        overflow re-fetch), bucketed to a power of two. Engages only
        once counts have been observed and only when it at least halves
        the copy (otherwise the full fetch is simpler and no slower)."""
        if not self.sized_transfer:
            return full_cap
        ewma = self.transfer_ewma.get(name)
        if ewma is None:
            return full_cap
        headroom = TRANSFER_HEADROOM * (
            OVERFLOW_BOOST_FACTOR if self.transfer_boost.get(name, 0) > 0
            else 1
        )
        cap = _pow2_ceil(
            max(int(ewma * headroom) + 1, MIN_TRANSFER_ROWS)
        )
        return cap if cap * 2 <= full_cap else full_cap

    def observe_transfer_counts(self, counts: Dict[str, int]) -> None:
        """Feed observed per-output valid counts into the EWMA (called
        from ``PendingBatch.collect``; an overflow re-fetch also bumps
        the EWMA straight to the observed count so the very next batch
        sizes correctly). Each observation also burns one batch off any
        post-overflow headroom boost."""
        a = TRANSFER_EWMA_ALPHA
        for n, c in counts.items():
            prev = self.transfer_ewma.get(n)
            self.transfer_ewma[n] = (
                float(c) if prev is None else a * c + (1.0 - a) * prev
            )
            boost = self.transfer_boost.get(n, 0)
            if boost > 0:
                self.transfer_boost[n] = boost - 1

    def _bump_transfer_stat(self, key: str) -> None:
        self.transfer_stats[key] = self.transfer_stats.get(key, 0) + 1

    # -- retrace accounting ------------------------------------------------
    def _step_cache_size(self) -> Optional[int]:
        try:
            return int(self._step._cache_size())
        except Exception:  # noqa: BLE001 — accounting only, never fails a batch
            return None

    def drain_retraces(self) -> int:
        """Jit re-traces since the last drain: explicit rebuilds
        (UDF refresh) plus jit-cache growth past the mark. The initial
        trace is expected — only growth BEYOND the accounted cache size
        counts (a dictionary-table resize or an input-shape change that
        silently re-traced the step)."""
        cur = self._step_cache_size()
        if cur is not None:
            if self._retrace_mark is None:
                self._retrace_mark = cur  # first trace: modeled, not drift
            elif cur > self._retrace_mark:
                self.retrace_count += cur - self._retrace_mark
                self._retrace_mark = cur
        n = self.retrace_count
        self.retrace_count = 0
        return n

    def refresh_mesh_collectives(self) -> None:
        """(Re)census the compiled mesh step's collectives — the
        observed side of the DX51x ICI conformance ratios. Called
        lazily at first collect (the step has compiled by then, so
        with a persistent compilation cache the extra ``compile()``
        deserializes) and again after any re-trace (the new program
        may partition differently — exactly what DX511 watches)."""
        if self.mesh is None or not self.mesh_observe:
            self.mesh_collectives = None
            return
        try:
            from ..dist.mesh import summarize_compiled

            lowered = self._step.lower(*self._step_input_avals())
            self.mesh_collectives = summarize_compiled(lowered.compile())
        except Exception as e:  # noqa: BLE001 — observability never fails a batch
            logger.warning("mesh collective census unavailable: %s", e)
            self.mesh_collectives = False  # don't retry every batch

    # -- AOT compile surface (the zero-cold-start path) --------------------
    def _source_raw_form(self, spec: SourceSpec) -> str:
        """The raw transfer form (and therefore trace signature) the
        AOT warm must use for this source — same rule as production
        dispatch (module-level ``source_raw_form``)."""
        return source_raw_form(spec.conf.get("inputtype"), self.mesh)

    def _warm_raw(self) -> Dict[str, Union[TableData, PackedRaw]]:
        """Zero-filled per-source raw batches in the exact form (and
        therefore trace signature) production dispatch will use."""
        raw: Dict[str, Union[TableData, PackedRaw]] = {}
        for name, spec in self.specs.items():
            if self._source_raw_form(spec) == "packed":
                np_cols = {
                    c: np.zeros(spec.capacity, _RAW_NP_DTYPES.get(t, np.int32))
                    for c, t in spec.raw_schema.types.items()
                }
                raw[name] = pack_raw(np_cols, np.zeros(spec.capacity, np.bool_))
            else:
                raw[name] = self._empty_raw(spec)
        return raw

    def _step_input_avals(self) -> tuple:
        """The 9-argument aval tuple of the fused step — the trace
        signature the jit cache keys on, derived from this processor's
        own device state (so it can never drift from what dispatch
        passes)."""
        def aval(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)

        tm = jax.tree_util.tree_map
        raw = {n: tm(aval, r) for n, r in self._warm_raw().items()}
        rings = tm(aval, self.window_buffers)
        state = tm(aval, self.state_data)
        refdata = {n: tm(aval, t) for n, (_s, t) in self.refdata.items()}
        scalar = jax.ShapeDtypeStruct((), jnp.int32)
        aux = tm(aval, self.aux_tables.tables())
        return (raw, rings, state, refdata, scalar, scalar, scalar, scalar,
                aux)

    def derive_compile_entries(self) -> List[dict]:
        """Every jit entry point this processor can ever dispatch, as
        manifest-shaped dicts (entry name, aval signature, static args,
        donation pattern) — the runtime side of the DX603 byte-
        exactness contract: the compile analyzer derives the same list
        statically from the flow config."""
        step_avals = self._step_input_avals()
        out_avals = jax.eval_shape(self._step_fn, *step_avals)[0]
        return compile_entries_from_avals(
            step_avals, out_avals,
            sized=self.sized_transfer, slots=self.output_slots_enabled,
        )

    def _warm_helpers(self) -> None:
        """Execute every reachable transfer-helper entry once — one
        ``_slice_table``/``_pack_slot`` per (output, capacity bucket)
        from the same lattice the manifest enumerates — so sized
        transfer never pays a first-use trace mid-stream."""
        step_avals = self._step_input_avals()
        out_avals = jax.eval_shape(self._step_fn, *step_avals)[0]
        for name in sorted(out_avals):
            t = out_avals[name]
            full_cap = int(t.valid.shape[0])
            sliceable = all(
                tuple(v.shape[:1]) == tuple(t.valid.shape)
                for v in t.cols.values()
            )
            zero_full = TableData(
                {c: jnp.zeros(a.shape, a.dtype) for c, a in t.cols.items()},
                jnp.zeros(t.valid.shape, t.valid.dtype),
            )
            caps = (
                transfer_buckets(full_cap) if self.sized_transfer
                else [full_cap]
            )
            for cap in caps:
                if self.output_slots_enabled and sliceable:
                    sliced = _slice_table(zero_full, cap)
                    _pack_slot(zero_full, sliced, cap)  # donates `sliced`
                elif cap < full_cap:
                    _slice_table(zero_full, cap)

    def _aot_warm(self) -> None:
        """AOT-compile every manifest entry at init instead of first
        dispatch: run one zero-filled batch through the jitted step
        (the exact production trace signature, so the first real
        dispatch hits a warm jit cache) and execute every reachable
        (output x capacity bucket) transfer helper once. With a
        persistent compilation cache configured
        (``process.compile.cachedir``/``.cacheurl``) the XLA compiles
        inside the warm resolve from the cache — hits/misses counted at
        cache-file granularity — and newly compiled entries are pushed
        back through ``objstore://`` so the NEXT start (restart,
        preemption recovery, scale-out replica) deserializes instead
        of compiling. A warm failure never kills init: the flow falls
        back to compile-at-first-dispatch, loudly."""
        t0 = time.time()
        cache = self._compile_cache
        pre_files = cache.file_count() if cache is not None else 0
        try:
            # manifest-vs-runtime drift check (the runtime face of
            # DX603): a manifest generated for a different flow shape
            # still warms — the signatures it promised just won't all
            # be the ones dispatch uses, which the drift count surfaces
            entries = self.derive_compile_entries()
            shipped = {
                e.get("entry"): e
                for e in (self.compile_manifest or {}).get("entries", [])
                if isinstance(e, dict)
            }
            drift = sum(
                1 for e in entries
                if e["entry"] not in shipped
                or shipped[e["entry"]].get("avals") != e["avals"]
                or list(shipped[e["entry"]].get("donate") or [])
                != list(e["donate"])
            )
            if drift:
                logger.warning(
                    "compile manifest drift (DX603): %d of %d entries "
                    "disagree with this flow's lowering — regenerate "
                    "the manifest", drift, len(entries),
                )
                self.compile_stats["ManifestDrift_Count"] = float(drift)
            # compile the fused step at the exact production trace
            # signature (zero-filled batch, production raw form) and
            # every reachable transfer helper. The warm batch is NEVER
            # collected: collect_tables() would overwrite the state
            # tables' standby snapshot with warm-derived rows — only
            # the counts sync (which completes the device work) runs.
            handle = self.dispatch_batch(self._warm_raw(), batch_time_ms=0)
            handle.collect_counts()
            handle.abandon()
            self._warm_helpers()
            self._aot_warmed = True
        except Exception:  # noqa: BLE001 — warm must never fail the flow
            logger.exception("AOT warm failed; first dispatch will compile")
        finally:
            # the warm batch must leave no trace in adaptive state: a
            # zero-count EWMA would size the first real batches at the
            # minimum bucket and force overflow re-fetches
            self.reset_state()
            self.transfer_ewma.clear()
            self.transfer_boost.clear()
            self.transfer_stats.clear()
        if cache is not None:
            try:
                new_files = cache.push()
                self.compile_stats["Cache_Hit_Count"] = float(pre_files)
                self.compile_stats["Cache_Miss_Count"] = float(new_files)
            except Exception as e:  # noqa: BLE001
                logger.warning("compile cache push failed: %s", e)
        self._warm_step_mark = self._step_cache_size()
        self.compile_stats["ColdStart_Ms"] = (time.time() - t0) * 1000.0

    def commit(self) -> None:
        """Commit state-table pointers after sinks succeed."""
        for st in self.state_tables.values():
            st.persist()

    def device_memory_stats(self) -> Optional[Dict[str, int]]:
        """The device allocator's live watermark — ``bytes_in_use`` /
        ``peak_bytes_in_use`` from ``memory_stats()`` of the device the
        step runs on (the first mesh device under a mesh). None when
        the backend doesn't report (CPU) — the host's Hbm_* sampler and
        the DX522 conformance check then stay silent."""
        try:
            if self.mesh is not None:
                dev = self.mesh.devices.flat[0]
            else:
                import jax

                dev = jax.local_devices()[0]
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — sampling is diagnostics only
            return None
        if not stats:
            return None
        return {
            "bytes_in_use": int(stats.get("bytes_in_use") or 0),
            "peak_bytes_in_use": int(
                stats.get("peak_bytes_in_use")
                or stats.get("bytes_in_use") or 0
            ),
        }


def _host_sort(rows: List[dict], order: List[Tuple[str, bool]]) -> None:
    """Stable multi-key in-place sort matching SQL semantics: ascending
    puts NULLs first, descending puts them last (Spark defaults).
    Applied least-significant key first so significance composes."""
    for key, asc in reversed(order):
        def kf(r, k=key):
            v = r.get(k)
            # the second element only compares within equal null-flags,
            # so the placeholder never meets a real value
            return (v is not None, v if v is not None else 0)

        rows.sort(key=kf, reverse=not asc)


def _pow2_ceil(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << max(int(n) - 1, 0).bit_length()


# placeholder for "no transfer in flight" while a freshly staged slot
# waits for its owning PendingBatch to be constructed
_SET_EVENT = threading.Event()
_SET_EVENT.set()


def _pack_impl(t: TableData, slot: TableData, cap: int) -> TableData:
    del slot  # consumed via donation: provides the output buffers
    return TableData(
        {c: v[:cap] if v.shape[:1] == t.valid.shape else v
         for c, v in t.cols.items()},
        t.valid[:cap],
    )


def _slice_impl(t: TableData, cap: int) -> TableData:
    return TableData(
        {c: v[:cap] if v.shape[:1] == t.valid.shape else v
         for c, v in t.cols.items()},
        t.valid[:cap],
    )


# per-capacity-bucket jit cache of the transfer helpers: one jitted
# closure per (helper kind, cap), LRU-evicted above the conf'd cap so
# a wandering EWMA (or many outputs x buckets) can never grow the
# cache — and its compiled executables — forever. Evictions are
# counted and drained into Compile_JitCacheEvict_Count at collect.
_HELPER_JIT_LOCK = threading.Lock()
_HELPER_JITS: "OrderedDict[Tuple[str, int], object]" = OrderedDict()
_jit_cache_cap = DEFAULT_JIT_CACHE_CAP
_jit_cache_evictions = 0


def set_jit_cache_cap(cap: int) -> None:
    global _jit_cache_cap
    _jit_cache_cap = max(1, int(cap))


def drain_jit_evictions() -> int:
    """Helper-jit LRU evictions since the last drain (process-wide)."""
    global _jit_cache_evictions
    with _HELPER_JIT_LOCK:
        n = _jit_cache_evictions
        _jit_cache_evictions = 0
        return n


def helper_jit_cache_size() -> int:
    with _HELPER_JIT_LOCK:
        return len(_HELPER_JITS)


def _helper_jit(kind: str, cap: int):
    global _jit_cache_evictions
    key = (kind, cap)
    with _HELPER_JIT_LOCK:
        fn = _HELPER_JITS.get(key)
        if fn is not None:
            _HELPER_JITS.move_to_end(key)
            return fn
        if kind == "slice":
            fn = jax.jit(functools.partial(_slice_impl, cap=cap))
        else:
            fn = jax.jit(
                functools.partial(_pack_impl, cap=cap), donate_argnums=(1,)
            )
        _HELPER_JITS[key] = fn
        while len(_HELPER_JITS) > _jit_cache_cap:
            _HELPER_JITS.popitem(last=False)
            _jit_cache_evictions += 1
        return fn


def _pack_slot(t: TableData, slot: TableData, cap: int) -> TableData:
    """Device-side pack of an (already compacted) output table into its
    donated transfer slot: identical math to ``_slice_table``, but the
    ``slot`` argument's buffers are DONATED, so XLA writes the result
    into the resident transfer-ready memory instead of allocating — the
    background D2H stream then always reads from one of two stable
    buffer sets per output. The caller guarantees the donated slot's
    previous transfer has landed (PendingBatch._landed)."""
    return _helper_jit("pack", cap)(t, slot)


def _slice_table(t: TableData, cap: int) -> TableData:
    """Device-side shrink of an (already compacted) output table to its
    sized transfer capacity — the D2H copy then moves ``cap`` rows
    instead of the full padded capacity. One compiled slice per
    (table layout, cap) pair; caps are power-of-two buckets
    (``transfer_buckets``), so the trace count stays logarithmic AND
    bounded (LRU above the jit-cache cap). The full-capacity source is
    deliberately NOT donated into the slice: the two-phase overflow
    fallback re-fetches it when ``counts_vec`` reveals the sized cap
    undershot."""
    return _helper_jit("slice", cap)(t)


# does this array type support copy_to_host_async? Probed ONCE per
# *backend array type* (the old probe ran once per process on the
# counts vector and assumed the answer for table arrays — a mixed
# backend, or a committed/donated array class with different transfer
# semantics, silently took the wrong path): capability misses are
# cached per type and counted per TABLE in
# Transfer_AsyncCopyFallback_Count; after a successful probe, transfer
# failures propagate to the batch loop like any other error.
_ASYNC_COPY_SUPPORT: Dict[type, bool] = {}


def _async_copy_supported(arr) -> bool:
    t = type(arr)
    cached = _ASYNC_COPY_SUPPORT.get(t)
    if cached is None:
        if not hasattr(arr, "copy_to_host_async"):
            cached = False
        else:
            try:
                arr.copy_to_host_async()  # idempotent enqueue
                cached = True
            except (AttributeError, NotImplementedError, TypeError):
                cached = False
        _ASYNC_COPY_SUPPORT[t] = cached
    return cached


def _host_table_nbytes(t: TableData) -> int:
    return sum(a.nbytes for a in t.cols.values()) + t.valid.nbytes


# batches at or below this capacity fetch counts + whole outputs in one
# device_get instead of syncing counts first and slicing on device —
# one host<->device round-trip instead of two (latency mode)
SMALL_FETCH_ROWS = 16384


@dataclass
class BatchCounts:
    """The parsed counts vector — everything the cheap blocking sync
    (``collect_counts``) learns about a batch: per-output valid row
    counts, the dropped-group/join overflow slots, and per-source
    projected input counts. A few hundred bytes on the wire; the output
    tables themselves stream in the background and resolve later via
    ``collect_tables``."""

    counts: np.ndarray  # the raw packed vector (nbytes = sync cost)
    dataset_counts: Dict[str, int]
    dropped_groups: Dict[str, int]
    dropped_joins: Dict[str, int]
    target_counts: Dict[str, int]


class PendingBatch:
    """An in-flight micro-batch: device work queued, results not yet
    fetched.

    Two-phase result path (the device-resident tail): the packed
    ``counts_vec`` and the (sized, slot-staged) output tables all start
    streaming device->host at dispatch; ``collect_counts()`` is the only
    BLOCKING device read — it resolves the counts vector (a few hundred
    bytes) and is the batch's sync point. ``collect_tables()`` then
    resolves the already-streaming table copies, materializes rows and
    persists state — typically on a background landing thread, so sinks
    ack out-of-band while the dispatch loop keeps feeding the device.
    ``collect()`` = counts + tables, the synchronous back-compat path
    (byte-identical results, golden-tested)."""

    def __init__(
        self, proc: "FlowProcessor", pipeline, out_datasets, state,
        counts_vec, batch_time_ms: int, base_ms: int, t0: float,
        out_names: Optional[List[str]] = None,
        target_names: Optional[List[str]] = None,
        fetch_tables: Optional[Dict[str, TableData]] = None,
        fetch_caps: Optional[Dict[str, int]] = None,
    ):
        self.proc = proc
        # THIS batch's pipeline: a UDF onInterval refresh may rebuild
        # proc.pipeline before an in-flight batch collects; its outputs
        # must decode against the schemas of the step that produced them
        self.pipeline = pipeline
        # likewise the dataset-name order the step packed counts in — a
        # refresh can reorder/shrink proc.output_datasets mid-flight
        self.out_names = (
            list(out_names) if out_names is not None
            else list(proc.output_datasets)
        )
        self.target_names = (
            list(target_names) if target_names is not None
            else [s.target for s in proc.specs.values()]
        )
        self.out_datasets = out_datasets
        # sized-transfer views: what start_fetch copies and collect
        # reads first; out_datasets stays the full-capacity fallback
        self.fetch_tables = (
            fetch_tables if fetch_tables is not None else dict(out_datasets)
        )
        self.fetch_caps = fetch_caps or {
            n: int(t.valid.shape[0]) for n, t in self.fetch_tables.items()
        }
        self.state = state  # THIS batch's state, for the A/B overwrite
        self.counts_vec = counts_vec
        self.batch_time_ms = batch_time_ms
        self.base_ms = base_ms
        self.t0 = t0
        self._prefetched = False
        # D2H accounting for this batch (Transfer_* metrics)
        self._d2h_bytes = 0
        self._transferred_rows = 0
        # parsed counts vector, cached by collect_counts (the sync
        # point happens at most once per batch)
        self._counts: Optional[BatchCounts] = None
        # set once the host copies of the fetch tables have landed (or
        # the batch is abandoned): the signal slot rotation checks
        # before donating this batch's transfer buffers to a new pack
        self._landed = threading.Event()
        # pooled ingest matrices this batch's raw inputs live in
        # (set by dispatch_batch); released exactly once, at landing or
        # abandon — the decode buffer pool's reuse gate
        self._ingest_buffers: List = []

    def _release_ingest(self) -> None:
        bufs, self._ingest_buffers = self._ingest_buffers, []
        for pool, mat in bufs:
            pool.release(mat)

    def abandon(self) -> None:
        """Mark a batch that will never be collected (window requeued
        after a failure): releases its transfer slots for donation and
        unblocks anyone coordinating on the landing."""
        if self._ingest_buffers:
            # the step may still be consuming the zero-copied ingest
            # matrices; wait for device completion before the pool may
            # hand them to a new decode (failure path — rare, cheap)
            try:
                jax.block_until_ready(self.counts_vec)
            except Exception:  # noqa: BLE001 — a failed step frees its inputs
                pass
        self._release_ingest()
        self._landed.set()

    def start_fetch(self) -> None:
        """Enqueue async device->host copies of everything collect()
        reads (counts + the SIZED output tables). Transport then
        overlaps the host's next-batch work instead of being paid as a
        blocking sync inside collect(). Transfers are latency-bound AND
        byte-bound on split hosts — so the sized (power-of-two bucketed)
        tables stream ahead of time, and only an overflow (detected from
        ``counts_vec`` at collect) pays a second round trip for the full
        table.

        Backend capability (``copy_to_host_async``) is probed once per
        backend ARRAY TYPE (counts vector and table arrays can differ —
        e.g. a donated slot class); an unsupported type falls back to
        the synchronous fetch in collect() and is counted PER TABLE in
        ``Transfer_AsyncCopyFallback_Count``. Real transfer errors are
        NOT swallowed — they propagate to the batch loop for retry."""
        if not _async_copy_supported(self.counts_vec):
            self.proc._bump_transfer_stat("AsyncCopyFallback")
            return
        self.counts_vec.copy_to_host_async()
        prefetched_all = True
        for t in self.fetch_tables.values():
            arrays = list(t.cols.values()) + [t.valid]
            if not all(_async_copy_supported(a) for a in arrays):
                # this table's array type can't stream: one fallback
                # count per table, not one blanket flag per batch
                self.proc._bump_transfer_stat("AsyncCopyFallback")
                prefetched_all = False
                continue
            for a in arrays:
                a.copy_to_host_async()
        self._prefetched = prefetched_all

    def block_until_evaluated(self) -> None:
        """Wait for the device step to COMPLETE (rule evaluation done,
        state advanced) without transferring results — the honest
        'rules evaluated' timestamp, independent of result transport."""
        jax.block_until_ready(self.counts_vec)

    def collect_counts(self) -> BatchCounts:
        """The batch's ONLY blocking device read: resolve the packed
        counts vector (layout: input count, per-output counts,
        per-output overflow slots for groups then joins, per-source
        projected counts — a few hundred bytes, already streaming since
        dispatch) and parse it. Idempotent; the sync point is paid at
        most once per batch."""
        if self._counts is not None:
            return self._counts
        with _trace_span("sync-counts"):
            counts = np.asarray(self.counts_vec)
        # unpack in PACKING order (snapshotted at dispatch) — jax returns
        # dict pytrees with sorted keys, so iterating out_datasets may
        # not match the order the step packed counts in
        names = self.out_names
        tnames = self.target_names
        self._counts = BatchCounts(
            counts=counts,
            dataset_counts={
                n: int(counts[1 + i]) for i, n in enumerate(names)
            },
            dropped_groups={
                n: int(counts[1 + len(names) + i])
                for i, n in enumerate(names)
                if int(counts[1 + len(names) + i]) >= 0
            },
            dropped_joins={
                n: int(counts[1 + 2 * len(names) + i])
                for i, n in enumerate(names)
                if int(counts[1 + 2 * len(names) + i]) >= 0
            },
            target_counts={
                t: int(counts[1 + 3 * len(names) + i])
                for i, t in enumerate(tnames)
            },
        )
        return self._counts

    def collect(self) -> Tuple[Dict[str, List[dict]], Dict[str, float]]:
        """Synchronous back-compat result path: counts sync + table
        landing in one call. Byte-identical to the split
        ``collect_counts()`` / ``collect_tables()`` background path
        (golden-tested in tests/test_sized_transfer.py)."""
        return self.collect_tables()

    def collect_tables(self) -> Tuple[Dict[str, List[dict]], Dict[str, float]]:
        """Resolve the background-streamed output tables, materialize
        rows and persist state; returns (datasets, metrics).

        With a prior ``start_fetch()`` (the default from
        ``dispatch_batch``) every device read below hits an
        already-landed host copy — this is the landing half the
        streaming host runs on its background transfer thread.
        Otherwise the device-compacted outputs are sliced to the true
        row counts ``collect_counts`` learned, so only real rows cross
        the device->host boundary, fetched in one batched device_get.
        """
        proc = self.proc
        bc = self.collect_counts()
        counts = bc.counts
        dataset_counts = bc.dataset_counts
        dropped_groups = bc.dropped_groups
        dropped_joins = bc.dropped_joins
        target_counts = bc.target_counts
        names = self.out_names
        try:
            with _trace_span("device-fetch"):
                if self._prefetched or proc.batch_capacity <= SMALL_FETCH_ROWS:
                    # sized/slot-staged tables, already streaming since
                    # dispatch — prefetched, or small enough that the
                    # extra bytes cost less than a second device slice
                    host_full = jax.device_get(self.fetch_tables)
                else:
                    host_full = None
            if host_full is not None:
                self._d2h_bytes = counts.nbytes + sum(
                    _host_table_nbytes(t) for t in host_full.values()
                )
                self._transferred_rows = sum(
                    int(t.valid.shape[0]) for t in host_full.values()
                )
                host_tables: Dict[str, TableData] = {}
                for n, t in host_full.items():
                    cnt = dataset_counts[n]
                    if cnt > int(t.valid.shape[0]):
                        # two-phase fallback: the sized prefetch undershot
                        # (count exceeds the adaptive capacity) — re-fetch
                        # the full-capacity table sliced to the true count.
                        # Rare by construction (EWMA + headroom + pow2
                        # bucket), loud in Transfer_Overflow_Count.
                        proc._bump_transfer_stat("Overflow")
                        # jump the EWMA straight to the observed count so
                        # the very next batch sizes above it, and double
                        # the headroom factor for the next
                        # OVERFLOW_BOOST_BATCHES batches so back-to-back
                        # bursts can't thrash the two-phase fetch
                        proc.transfer_ewma[n] = float(cnt)
                        proc.transfer_boost[n] = OVERFLOW_BOOST_BATCHES
                        full = self.out_datasets[n]
                        with _trace_span("device-refetch"):
                            t = jax.device_get(TableData(
                                {c: v[:cnt]
                                 if v.shape[:1] == full.valid.shape else v
                                 for c, v in full.cols.items()},
                                full.valid[:cnt],
                            ))
                        self._d2h_bytes += _host_table_nbytes(t)
                        self._transferred_rows += cnt
                        host_tables[n] = t
                    else:
                        host_tables[n] = TableData(
                            {c: v[:cnt] if v.shape[:1] == t.valid.shape else v
                             for c, v in t.cols.items()},
                            t.valid[:cnt],
                        )
            else:
                # counts-first path (large batch, no prefetch): slice on
                # device to the exact counts, then one batched device_get —
                # already the wire minimum, sized transfer adds nothing
                sliced = {
                    n: TableData(
                        {c: v[: dataset_counts[n]]
                         if v.shape[:1] == t.valid.shape else v
                         for c, v in t.cols.items()},
                        t.valid[: dataset_counts[n]],
                    )
                    for n, t in self.out_datasets.items()
                }
                host_tables = jax.device_get(sliced)
                self._d2h_bytes = counts.nbytes + sum(
                    _host_table_nbytes(t) for t in host_tables.values()
                )
                self._transferred_rows = sum(dataset_counts.values())
        finally:
            # host copies landed (or the fetch failed): this batch's
            # transfer slots are safe to donate to a future pack, and
            # its pooled ingest matrices (fully consumed by the step,
            # which completed at the counts sync) return to the pool
            self._release_ingest()
            self._landed.set()

        # armed sanitizer: every landed host table is scanned for
        # sentinel leakage BEFORE materialization — a poisoned pool slot
        # showing through a sink payload is the use-after-release the
        # static pass (DX800/DX801) exists to prevent
        if proc.buffer_sanitizer is not None:
            for name, table in host_tables.items():
                proc.buffer_sanitizer.scan_table(name, table)

        datasets: Dict[str, List[dict]] = {}
        with _trace_span("materialize"):
            for name, table in host_tables.items():
                rows = materialize_rows(
                    table, self.pipeline.schema_of(name), proc.dictionary,
                    self.base_ms,
                )
                view = self.pipeline.view_by_name(name)
                if view is not None and view.host_order:
                    # ORDER BY over computed-string columns: the device
                    # has no id to sort by, so the ordering (and limit)
                    # applies to the materialized rows (planner
                    # host-order path)
                    _host_sort(rows, view.host_order)
                    if view.host_limit is not None:
                        rows = rows[: view.host_limit]
                datasets[name] = rows

        # persist state tables (A/B overwrite; persist() is the caller's
        # post-sink commit, see StreamingHost) — from THIS batch's state
        for sname, st in proc.state_tables.items():
            st.overwrite(self.state[sname], proc.dictionary)

        elapsed_ms = (time.time() - self.t0) * 1000.0
        metrics = {
            "Latency-Process": elapsed_ms,
            "BatchProcessedET": float(self.batch_time_ms),
        }
        for t, c in target_counts.items():
            metrics[f"Input_{t}_Events_Count"] = float(c)
        for n, c in dataset_counts.items():
            metrics[f"Output_{n}_Events_Count"] = float(c)
        for n, c in dropped_groups.items():
            metrics[f"Output_{n}_GroupsDropped"] = float(c)
        for n, c in dropped_joins.items():
            metrics[f"Output_{n}_JoinRowsDropped"] = float(c)
        # drain host-side ingest counters accumulated since last collect
        if proc.ingest_stats:
            for k, v in proc.ingest_stats.items():
                if v:
                    metrics[f"Input_{k}_Count"] = float(v)
            proc.ingest_stats.clear()
        # ingest decode fast-path gauges (native/decoder.cpp): the
        # shard count in effect, the last measured decode rate, and
        # buffer-pool reuses since the last collect — the runtime face
        # of the BENCH decoder_rows_per_sec / shard-curve numbers
        if proc._decode_shards is not None:
            metrics["Decode_Shards"] = float(proc._decode_shards)
        if proc._decode_rows_per_sec is not None:
            metrics["Decode_RowsPerSec"] = float(proc._decode_rows_per_sec)
        if proc._ingest_pools:
            reuse = sum(
                p.take_reuse_count() for p in proc._ingest_pools.values()
            )
            if reuse:
                metrics["Decode_BufferReuse_Count"] = float(reuse)
        if proc.dictionary.overflow_count:
            metrics["Input_string_dictionary_overflow_Count"] = float(
                proc.dictionary.overflow_count
            )
            proc.dictionary.overflow_count = 0
        # on_interval hooks that threw since the last collect: their
        # refreshes were skipped (previous trace kept serving) — loud
        # in metrics, invisible to the batch loop
        if proc.udf_refresh_errors:
            metrics["UdfRefreshError"] = float(proc.udf_refresh_errors)
            proc.udf_refresh_errors = 0
        # jit re-traces since the last collect (refresh rebuilds +
        # cache-miss growth) — the conformance monitor's DX503 input
        retraces = proc.drain_retraces()
        if retraces:
            metrics["Retrace_Count"] = float(retraces)
        # observed mesh communication: the executed program's collective
        # census as per-batch series (the DX510/DX511 inputs). A
        # re-trace re-censuses — the new program may partition
        # differently, which is precisely the drift DX511 detects.
        if proc.mesh is not None and proc.mesh_observe:
            if proc.mesh_collectives is None or retraces:
                proc.refresh_mesh_collectives()
            mc = proc.mesh_collectives
            if mc:
                metrics["Mesh_ICI_Bytes"] = mc.wire_bytes(proc.mesh.size)
                metrics["Mesh_Reshard_Count"] = float(mc.op_count)
        # warm-start promise check (the DX604 input): the AOT warm left
        # the step's jit cache at _warm_step_mark; growth past it means
        # a dispatch compiled even though a warm start was promised
        if proc._aot_warmed and proc._warm_step_mark is not None:
            cur = proc._step_cache_size()
            if cur is not None and cur > proc._warm_step_mark:
                proc.compile_stats["WarmMiss_Count"] = (
                    proc.compile_stats.get("WarmMiss_Count", 0.0)
                    + float(cur - proc._warm_step_mark)
                )
                proc._warm_step_mark = cur
        # transfer-helper jit LRU evictions + one-shot compile stats
        # (cold-start ms, persistent-cache hits/misses, warm misses)
        evictions = drain_jit_evictions()
        if evictions:
            metrics["Compile_JitCacheEvict_Count"] = float(evictions)
        if proc.compile_stats:
            for k, v in proc.compile_stats.items():
                metrics[f"Compile_{k}"] = float(v)
            proc.compile_stats.clear()
        # sized-transfer accounting: bytes actually moved D2H for this
        # batch and the valid/transferred row ratio (1.0 = wire minimum)
        if names:
            valid_rows = sum(dataset_counts.values())
            metrics["Transfer_D2HBytes"] = float(self._d2h_bytes)
            metrics["Transfer_Efficiency"] = (
                valid_rows / self._transferred_rows
                if self._transferred_rows else 1.0
            )
        # partitioned-state accounting: the partition geometry this
        # replica runs (gauges) plus the deltas since the last collect
        # — load fallbacks (DX530/531), snapshot pushes/pulls through
        # the objstore mirror, the successor handoff cost, and rows the
        # key-routed ingest filter dropped as un-owned
        if proc.state_tables or proc.state_replica_count > 1:
            metrics["State_Partition_Count"] = float(proc.state_partitions)
            metrics["State_Partition_Owned"] = float(len(proc.state_owned))
        if proc.state_stats:
            for k, v in proc.state_stats.items():
                metrics[f"State_{k}"] = float(v)
            proc.state_stats.clear()
        # bytes the blocking counts-only sync moved — the whole
        # synchronous wire cost of the batch tail (everything else
        # streams in the background)
        metrics["Sync_CountsBytes"] = float(counts.nbytes)
        # sanitizer accounting: views guarded since the last collect,
        # and (only when nonzero — silence is health) poison hits
        if proc.buffer_sanitizer is not None:
            metrics.update(proc.buffer_sanitizer.drain_metric_deltas())
        if proc.transfer_stats:
            for k, v in proc.transfer_stats.items():
                metrics[f"Transfer_{k}_Count"] = float(v)
            proc.transfer_stats.clear()
        # feed the adaptive capacity for the NEXT batches
        proc.observe_transfer_counts(dataset_counts)
        return datasets, metrics
