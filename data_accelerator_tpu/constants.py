"""Product-wide constants.

Mirrors the reference's ``datax-core`` constants package
(``DataProcessing/datax-core/src/main/scala/datax/constants/*.scala``) so
that flow configs, metric names and dataset names written for the
reference keep their meaning here.
"""

import os

# reference: NamePrefix.scala:8-11
NAME_PREFIX = os.environ.get("DATAX_NAMEPREFIX", "DataX")


class ProductConstant:
    """reference: ProductConstant.scala:8-22"""

    DefaultAppName = f"{NAME_PREFIX}_Unknown_App"
    MetricAppNamePrefix = f"{NAME_PREFIX}-".upper()
    ProductRoot = NAME_PREFIX.lower()
    ProductJobTags = f"{NAME_PREFIX}JobTags"
    ProductOutputFilter = f"{NAME_PREFIX}OutputFilter"
    # regex matching a query-separator line
    ProductQuery = rf"^--{NAME_PREFIX}Query--"
    # the states separator introducing accumulation-table DDL
    # (reference: DataX.Flow.CodegenRules/Engine.cs rule-state handling)
    ProductStates = rf"^--{NAME_PREFIX}States--"


class ColumnName:
    """reference: ColumnName.scala:10-25"""

    RawObjectColumn = "Raw"
    EventNameColumn = "EventName"
    PropertiesColumn = f"{NAME_PREFIX}Properties"
    RawPropertiesColumn = "Properties"
    RawSystemPropertiesColumn = "SystemProperties"
    InternalColumnPrefix = f"__{NAME_PREFIX}_"
    InternalColumnFileInfo = InternalColumnPrefix + "FileInfo"
    MetadataColumnPrefix = f"__{NAME_PREFIX}Metadata_"
    MetadataColumnOutputPartitionTime = MetadataColumnPrefix + "OutputPartitionTime"
    OutputGroupColumn = f"{NAME_PREFIX}OutputGroup"


class DatasetName:
    """reference: DatasetName.scala:8-13"""

    DataStreamRaw = f"{NAME_PREFIX}RawInput"
    DataStreamProjection = f"{NAME_PREFIX}ProcessedInput"
    DataStreamProjectionBatch = f"{NAME_PREFIX}ProcessedInput_Batch"
    DataStreamProjectionWithWindow = f"{NAME_PREFIX}ProcessedInput_Window"


class JobArgument:
    """reference: JobArgument.scala:9-21 — env-var names the job honors."""

    ConfNamePrefix = f"{NAME_PREFIX}_".upper()
    ConfName_AppConf = ConfNamePrefix + "APPCONF"
    ConfName_AppName = ConfNamePrefix + "APPNAME"
    ConfName_LogLevel = ConfNamePrefix + "LOGLEVEL"
    ConfName_CheckpointEnabled = ConfNamePrefix + "CHECKPOINTENABLED"
    ConfName_BlobWriterTimeout = ConfNamePrefix + "BlobWriterTimeout"


class MetricName:
    """reference: MetricName.scala:8"""

    MetricSinkPrefix = "Sink_"


class ProcessingPropertyName:
    """reference: ProcessingPropertyName.scala:8-14"""

    BlobPathHint = "Partition"
    BatchTime = "BatchTime"
    BlobTime = "InputTime"
    CPTime = "CPTime"
    CPExecutor = "CPExecutor"


class FeatureName:
    """reference: FeatureName.scala:8-10"""

    FunctionDisableCommonCaching = "disableCommonCaching"
