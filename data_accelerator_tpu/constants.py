"""Product-wide constants.

Mirrors the reference's ``datax-core`` constants package
(``DataProcessing/datax-core/src/main/scala/datax/constants/*.scala``) so
that flow configs, metric names and dataset names written for the
reference keep their meaning here.
"""

import os

# reference: NamePrefix.scala:8-11
NAME_PREFIX = os.environ.get("DATAX_NAMEPREFIX", "DataX")


class ProductConstant:
    """reference: ProductConstant.scala:8-22"""

    DefaultAppName = f"{NAME_PREFIX}_Unknown_App"
    MetricAppNamePrefix = f"{NAME_PREFIX}-".upper()
    ProductRoot = NAME_PREFIX.lower()
    ProductJobTags = f"{NAME_PREFIX}JobTags"
    ProductOutputFilter = f"{NAME_PREFIX}OutputFilter"
    # regex matching a query-separator line
    ProductQuery = rf"^--{NAME_PREFIX}Query--"
    # the states separator introducing accumulation-table DDL
    # (reference: DataX.Flow.CodegenRules/Engine.cs rule-state handling)
    ProductStates = rf"^--{NAME_PREFIX}States--"


class ColumnName:
    """reference: ColumnName.scala:10-25"""

    RawObjectColumn = "Raw"
    EventNameColumn = "EventName"
    PropertiesColumn = f"{NAME_PREFIX}Properties"
    RawPropertiesColumn = "Properties"
    RawSystemPropertiesColumn = "SystemProperties"
    InternalColumnPrefix = f"__{NAME_PREFIX}_"
    InternalColumnFileInfo = InternalColumnPrefix + "FileInfo"
    MetadataColumnPrefix = f"__{NAME_PREFIX}Metadata_"
    MetadataColumnOutputPartitionTime = MetadataColumnPrefix + "OutputPartitionTime"
    OutputGroupColumn = f"{NAME_PREFIX}OutputGroup"


class DatasetName:
    """reference: DatasetName.scala:8-13"""

    DataStreamRaw = f"{NAME_PREFIX}RawInput"
    DataStreamProjection = f"{NAME_PREFIX}ProcessedInput"
    DataStreamProjectionBatch = f"{NAME_PREFIX}ProcessedInput_Batch"
    DataStreamProjectionWithWindow = f"{NAME_PREFIX}ProcessedInput_Window"


class JobArgument:
    """reference: JobArgument.scala:9-21 — env-var names the job honors."""

    ConfNamePrefix = f"{NAME_PREFIX}_".upper()
    ConfName_AppConf = ConfNamePrefix + "APPCONF"
    ConfName_AppName = ConfNamePrefix + "APPNAME"
    ConfName_LogLevel = ConfNamePrefix + "LOGLEVEL"
    ConfName_CheckpointEnabled = ConfNamePrefix + "CHECKPOINTENABLED"
    ConfName_BlobWriterTimeout = ConfNamePrefix + "BlobWriterTimeout"


class MetricName:
    """reference: MetricName.scala:8 — extended with the registry of
    every metric name the ENGINE itself emits (user flows may add
    arbitrary names via ``OUTPUT ... TO Metrics``; those are data, not
    registry members).

    The registry is the contract between the runtime, the Prometheus
    exposition, the SPA dashboard and OBSERVABILITY.md — a tier-1 test
    asserts emitted names match it, so a renamed metric cannot silently
    orphan a dashboard tile (the ANALYSIS.md-registry sync pattern).
    """

    MetricSinkPrefix = "Sink_"
    LatencyPrefix = "Latency-"

    # fleet telemetry plane (obs/publisher.py + obs/fleetview.py):
    # publisher self-metrics and aggregator-side counters, referenced
    # by name from both modules so the emit sites and the registry
    # cannot drift
    FLEET_FRAMES = "Fleet_Frames_Count"
    FLEET_FRAME_BYTES = "Fleet_Frame_Bytes"
    FLEET_FRAME_PUBLISH_MS = "Fleet_FramePublish_Ms"
    FLEET_FRAME_PUBLISH_ERROR = "Fleet_FramePublishError_Count"
    FLEET_FRAME_DECODE_ERROR = "Fleet_FrameDecodeError_Count"
    FLEET_MERGE_LATENCY_MS = "Fleet_MergeLatency_Ms"

    # runtime conf audit (runtime/confaudit.py, armed at every
    # StreamingHost / LiveQueryService init): keys audited against the
    # conf registry, keys no registry row governs, and keys whose
    # value violated its row's type/bounds — runtime DX1006, the
    # dynamic half of the DX10xx configuration-lattice analyzer
    CONF_AUDITED = "Conf_Audited_Count"
    CONF_UNKNOWN = "Conf_Unknown_Count"
    CONF_OUT_OF_BOUNDS = "Conf_OutOfBounds_Count"
    # delivery-conservation audit counters (obs/fleetview.py DX54x)
    DELIVERY_LOSS = "Conformance_Delivery_Loss_Count"
    DELIVERY_DUPLICATE = "Conformance_Delivery_Duplicate_Count"
    DELIVERY_STALE_REPLICA = "Conformance_Delivery_StaleReplica_Count"

    # canonical per-batch stage names (span names == histogram stages ==
    # the <stage> of Latency-<stage> metrics, modulo capitalization),
    # plus the LiveQuery serving plane's end-to-end execute stage
    # ("lq-exec" -> Latency-LQExec, see _STAGE_METRIC_OVERRIDES) — a
    # STAGES member so alert rules over Latency-LQExec-pNN resolve
    # through the live histograms like every other stage
    STAGES = (
        "decode", "dispatch", "device-step", "sync", "collect",
        "sinks", "checkpoint", "batch", "lq-exec",
    )

    # stages whose metric stem is not the plain CamelCase of the stage
    # name (acronym casing)
    _STAGE_METRIC_OVERRIDES = {"lq-exec": "Latency-LQExec"}

    # regexes over the metric part of ``DATAX-<flow>:<metric>`` covering
    # everything the engine emits at runtime (host + processor + sinks +
    # histogram percentile series). Anchored full-match.
    RUNTIME_METRIC_PATTERNS = (
        # raw per-batch latencies (back-compat dashboard series)
        r"Latency-(Batch|Process)",
        # per-stage histogram percentiles (obs/histogram.py)
        r"Latency-(Decode|Dispatch|DeviceStep|Sync|Collect|Sinks|"
        r"Checkpoint|Batch)-p(50|95|99)",
        r"BatchProcessedET",
        r"IngestRateScale",
        r"Input_[A-Za-z0-9_.]+_Events_Count",
        r"Input_[A-Za-z0-9_.]+_Count",
        # Kafka record batches skipped by the per-batch CRC-32C check
        # (runtime/kafka_wire.py decode_record_batches + the native
        # walker) — covered by the Input_*_Count family above, listed
        # explicitly because the pilot/alert surfaces reference it
        r"Input_CorruptBatch_Count",
        # ingest decode fast path (native/decoder.cpp via
        # runtime/processor.py encode_json_bytes): conf'd decoder shard
        # count in effect, last measured decode rate, and reuses of the
        # pooled transfer-ready ingest matrices since the last collect
        r"Decode_Shards",
        r"Decode_RowsPerSec",
        r"Decode_BufferReuse_Count",
        r"Output_[A-Za-z0-9_.]+_Events_Count",
        r"Output_[A-Za-z0-9_.]+_(GroupsDropped|JoinRowsDropped)",
        r"Sink_[a-z]+",
        r"Batch_Files_Count",
        # UDF on_interval hooks that threw (refresh skipped, previous
        # trace kept serving — runtime/processor.py dispatch_batch)
        r"UdfRefreshError",
        # depth-N pipelined window (runtime/host.py run_pipelined):
        # in-flight depth at finish time + ms the dispatch loop stalled
        # waiting for the window's oldest batch
        r"Pipeline_Depth",
        r"Pipeline_Stall_Ms",
        # sized output transfer (runtime/processor.py PendingBatch):
        # D2H bytes per batch, valid/transferred row ratio, and the
        # async-copy-capability / sized-cap-overflow / slot-contention
        # fallback counters
        r"Transfer_D2HBytes",
        r"Transfer_Efficiency",
        r"Transfer_(AsyncCopyFallback|Overflow|SlotContended)_Count",
        # buffer sanitizer (runtime/sanitizer.py, armed via
        # process.debug.buffersanitizer): buffers guarded per collect,
        # and use-after-release detections — runtime DX805, the dynamic
        # half of the DX8xx buffer-lifetime analyzer
        r"Sanitizer_GuardedViews_Count",
        r"Sanitizer_PoisonHit_Count",
        # protocol monitor (runtime/protocolmonitor.py, armed via
        # process.debug.protocolmonitor): delivery-protocol events
        # recorded per batch tail, and sealed-batch ordering violations
        # — runtime DX906, the dynamic half of the DX9xx exactly-once
        # protocol analyzer
        r"Protocol_Events_Count",
        r"Protocol_Violation_Count",
        # conf audit (runtime/confaudit.py, armed at host/LQ-service
        # init): process-namespace keys audited against the typed conf
        # registry (analysis/confspec.py), unknown keys, and
        # type/bounds violations — runtime DX1006, the dynamic half of
        # the DX10xx configuration-lattice analyzer
        r"Conf_Audited_Count",
        r"Conf_Unknown_Count",
        r"Conf_OutOfBounds_Count",
        # device-resident result path (runtime/processor.py
        # collect_counts + runtime/host.py background landing): bytes
        # the blocking counts-only sync moved, landings still queued
        # when a batch's tail was submitted to the background transfer
        # thread, and the ms its streamed tables took to resolve there
        r"Sync_CountsBytes",
        r"Transfer_Background_(Pending|LandMs)",
        # jit re-traces observed since the last collect (UDF refresh
        # rebuilds + shape/dictionary-growth cache misses); the
        # conformance monitor's DX503 input
        r"Retrace_Count",
        # observed mesh communication (dist/mesh.py collective_summary,
        # exported by the mesh processor per batch): ring-convention
        # wire bytes of the executed program's collectives and its
        # collective-op count — the runtime counterpart of the DX7xx
        # sharding model, judged by the DX510/DX511 conformance checks
        r"Mesh_ICI_Bytes",
        r"Mesh_Reshard_Count",
        # model-vs-observed conformance (obs/conformance.py): windowed
        # observed/predicted ratios against the cost-model report
        # embedded in the conf, plus the cumulative drift-event count
        r"Conformance_D2HBytes_Ratio",
        r"Conformance_Occupancy_[A-Za-z0-9_.]+_Ratio",
        # mesh ICI drift ratio (observed Mesh_ICI_Bytes / the embedded
        # sharding model's wire prediction — the DX510 gauge)
        r"Conformance_MeshIci_Ratio",
        # roofline time-model conformance (obs/conformance.py DX520/
        # DX521): observed per-stage latency p50 / the calibrated
        # roofline prediction, one gauge per predicted stage
        r"Conformance_StageTime_[A-Za-z]+_Ratio",
        # live HBM peak / the DX2xx modeled footprint (the DX522 gauge)
        r"Conformance_Hbm_Ratio",
        r"Conformance_Drift_Count",
        # calibrated machine profile (obs/calibrate.py): the measured
        # constants the roofline predictions are priced with — HBM
        # read/write GB/s, dense GFLOP/s, per-dispatch overhead µs,
        # D2H GB/s and (under a mesh) ICI GB/s
        r"Calib_HbmReadGBps",
        r"Calib_HbmWriteGBps",
        r"Calib_FlopsGFlops",
        r"Calib_DispatchOverheadUs",
        r"Calib_D2HGBps",
        r"Calib_IciGBps",
        # measured host JSON-decode rate (native decoder probe) — the
        # constant pricing the latency model's decode term, the DX520
        # baseline for stage_decode_ms
        r"Calib_DecodeRowsPerSec",
        # live HBM watermark sampler (runtime/processor.py
        # device_memory_stats, exported per batch when the backend
        # reports allocator stats)
        r"Hbm_BytesInUse",
        r"Hbm_PeakBytes",
        # on-demand profiler surface (obs/profiler.py): cumulative
        # finished captures this host has written
        r"Profiler_Captures_Count",
        # AOT compile + persistent compilation cache
        # (runtime/processor.py process.compile.*): init-time warm cost,
        # persistent-cache hit/miss counts at cache-entry granularity,
        # warm-start promises missed (a dispatch compiled after an AOT
        # warm — the runtime face of DX604), shipped-manifest drift
        # detected at warm time (the runtime face of DX603), and
        # LRU evictions from the bounded transfer-helper jit caches
        r"Compile_ColdStart_Ms",
        r"Compile_Cache_(Hit|Miss)_Count",
        r"Compile_WarmMiss_Count",
        r"Compile_ManifestDrift_Count",
        r"Compile_JitCacheEvict_Count",
        # alert engine (obs/alerts.py): count of currently-firing rules,
        # exported every evaluation so dashboards can chart alert state
        r"Alerts_Firing",
        # autopilot (pilot/controller.py, exported once per evaluation
        # window): cumulative actuations applied / decisions held by
        # budget+cooldown, the live pipeline depth the controller is
        # running, and the backpressure token-bucket balance
        r"Pilot_Actuations_Count",
        r"Pilot_Suppressed_Count",
        r"Pilot_Depth",
        r"Pilot_Backpressure_Tokens",
        # partitioned state & rescale (runtime/statetable.py +
        # runtime/statepartition.py, drained at collect; the
        # Partition_Reassigned count is emitted under DATAX-Fleet by
        # JobOperation.rescale): partition geometry this replica runs,
        # successor handoff cost (state pull + restore at init),
        # corrupt-snapshot fallbacks (DX530/531), snapshot pushes/pulls
        # through the objstore mirror, rows the key-routed ingest
        # filter dropped as un-owned, and window rows dropped when a
        # merge overflowed a ring slot
        r"State_Partition_Count",
        r"State_Partition_Owned",
        r"State_Partition_Reassigned_Count",
        r"State_Handoff_Ms",
        r"State_LoadFallback_Count",
        r"State_Snapshot_(Push|Pull)_Count",
        r"State_IngestFiltered_Count",
        r"State_WindowRows_Dropped_Count",
        # fleet placement (serve/jobs.py FleetAdmissionGate, emitted
        # under the DATAX-Fleet app on every admission check / re-plan):
        # fleet-wide chip/flow counts, per-chip packed HBM and
        # utilization from the DX4xx placement plan, admission
        # rejections, and re-plan rounds (serve/scheduler.py
        # PlacementReplanner)
        r"Fleet_Chips",
        r"Fleet_Flows(Placed|Unplaced)",
        r"Fleet_MaxChipUtilization",
        r"Fleet_Chip[0-9]+_(HbmBytes|Utilization)",
        r"Fleet_AdmissionRejected_Count",
        r"Placement_Replans_Count",
        # fleet telemetry plane (obs/publisher.py frames published /
        # last frame bytes / publish latency / failed publishes, and
        # obs/fleetview.py corrupt frames skipped, cross-replica merge
        # latency, replica liveness gauges)
        r"Fleet_Frames_Count",
        r"Fleet_Frame_Bytes",
        r"Fleet_FramePublish_Ms",
        r"Fleet_FramePublishError_Count",
        r"Fleet_FrameDecodeError_Count",
        r"Fleet_MergeLatency_Ms",
        r"Fleet_(Replicas|StaleReplicas)_Count",
        # delivery-conservation audit (obs/fleetview.py DX540/541/542):
        # cumulative audit findings per flow over the merged lineage
        r"Conformance_Delivery_(Loss|Duplicate|StaleReplica)_Count",
        # LiveQuery serving plane (lq/service.py, exported under the
        # DATAX-LiveQuery app): live session/tenant gauges, completed
        # execute QPS over a trailing 10 s window, queued-not-yet-
        # dispatched calls (the pilot-visible pressure signal the
        # lq-latency-slo alert rule votes backpressure on), mean calls
        # merged per dispatch tick, cumulative device dispatches and
        # calls that shared one (the coalescing win), resident
        # warm-kernel HBM priced by the DX2xx model, LRU evictions from
        # the modeled budget, and typed admission/quota rejections
        # (rejected calls never reach a device dispatch)
        r"LQ_Sessions",
        r"LQ_Tenants",
        r"LQ_Qps",
        r"LQ_Backlog",
        r"LQ_CoalesceFanin",
        r"LQ_Dispatch_Count",
        r"LQ_Coalesced_Count",
        r"LQ_KernelBytes",
        r"LQ_KernelEvict_Count",
        r"LQ_Admission_Rejected_Count",
        # end-to-end LiveQuery execute latency (queue wait + coalesced
        # dispatch), the serving plane's interactive-latency histogram
        # (exemplar-bearing like every Latency-* family)
        r"Latency-LQExec-p(50|95|99)",
    )

    @classmethod
    def is_runtime_metric(cls, metric: str) -> bool:
        """True when ``metric`` (the part after ``DATAX-<flow>:``) is a
        registered engine-emitted name."""
        import re

        return any(
            re.fullmatch(p, metric) for p in cls.RUNTIME_METRIC_PATTERNS
        )

    @staticmethod
    def metric_app_name(job_name: str) -> str:
        """The ``DATAX-<job>`` metric app key a flow's series live
        under in the shared MetricStore (the runtime derives the same
        via ``SettingDictionary.get_metric_app_name``; the fleet
        analyzer's DX412 series-collision lint derives it statically
        from the flow name)."""
        return ProductConstant.MetricAppNamePrefix + job_name

    @classmethod
    def stage_metric(cls, stage: str) -> str:
        """Histogram stage -> its metric stem, e.g. ``device-step`` ->
        ``Latency-DeviceStep`` (acronym stages override: ``lq-exec`` ->
        ``Latency-LQExec``)."""
        override = cls._STAGE_METRIC_OVERRIDES.get(stage)
        if override is not None:
            return override
        camel = "".join(w.capitalize() for w in stage.split("-"))
        return f"Latency-{camel}"


class ProcessingPropertyName:
    """reference: ProcessingPropertyName.scala:8-14"""

    BlobPathHint = "Partition"
    BatchTime = "BatchTime"
    BlobTime = "InputTime"
    CPTime = "CPTime"
    CPExecutor = "CPExecutor"


class FeatureName:
    """reference: FeatureName.scala:8-10"""

    FunctionDisableCommonCaching = "disableCommonCaching"
