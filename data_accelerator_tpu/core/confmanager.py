"""Config assembly: CLI args + ``DATAX_*`` env vars + ``.conf`` file.

reference: datax-host ConfigManager.scala:18-136, utility/ArgumentsParser
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Sequence

from ..constants import JobArgument
from .config import (
    EngineException,
    SettingDictionary,
    parse_conf_lines,
)


def get_named_args(args: Sequence[str]) -> Dict[str, str]:
    """Parse ``key=value`` CLI arguments (reference: ArgumentsParser.scala)."""
    named: Dict[str, str] = {}
    for a in args:
        pos = a.find("=")
        if pos > 0:
            named[a[:pos].strip()] = a[pos + 1:].strip()
    return named


class ConfigManager:
    """Process-wide configuration singleton.

    reference: ConfigManager.scala:18-81 (double-checked-locking singleton)
    """

    _lock = threading.Lock()
    _active: Optional[SettingDictionary] = None

    @classmethod
    def _local_env_vars(cls) -> Dict[str, str]:
        prefix = JobArgument.ConfNamePrefix
        return {k: v for k, v in os.environ.items() if k.startswith(prefix)}

    @classmethod
    def get_active_dictionary(cls) -> SettingDictionary:
        if cls._active is None:
            with cls._lock:
                if cls._active is None:
                    cls._active = SettingDictionary(cls._local_env_vars())
        return cls._active

    @classmethod
    def set_active_dictionary(cls, conf: SettingDictionary) -> None:
        with cls._lock:
            cls._active = conf

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._active = None

    @classmethod
    def get_configuration_from_arguments(
        cls, args: Sequence[str]
    ) -> SettingDictionary:
        """Merge env + CLI into the active dictionary.

        reference: ConfigManager.scala:61-81
        """
        named = get_named_args(args)
        if "conf" not in named:
            raise EngineException("configuration file is not specified.")
        envs = cls._local_env_vars()
        converted = {
            JobArgument.ConfName_AppConf: named.get("conf"),
            JobArgument.ConfName_LogLevel: named.get("executorLogLevel"),
            JobArgument.ConfName_CheckpointEnabled: named.get("checkpointEnabled"),
        }
        converted = {k: v for k, v in converted.items() if v is not None}
        merged = {**envs, **named, **converted}
        conf = SettingDictionary(merged)
        cls.set_active_dictionary(conf)
        return conf

    @classmethod
    def load_config(cls, conf_file: Optional[str] = None) -> SettingDictionary:
        """Read the flat ``.conf`` file and merge into the active dictionary.

        ``${token}`` placeholders in values are substituted from the already
        merged dictionary (reference: ConfigManager.scala:117-126).
        """
        d = cls.get_active_dictionary()
        path = conf_file or d.get_app_configuration_file()
        if path is None:
            raise EngineException("No conf file is provided")
        if not path.lower().endswith(".conf"):
            raise EngineException(
                "non-conf file is not supported as configuration input"
            )
        from ..serve.objectstore import fetch_objstore_url, is_objstore_url

        if is_objstore_url(path):
            # conf generated into the shared object store by the control
            # plane (serve/storage.py ObjectRuntimeStorage) — workers on
            # any host read it through the store, the role wasbs:// blob
            # paths play for the reference's cluster jobs
            text = fetch_objstore_url(
                path, token=os.environ.get("DATAX_OBJSTORE_TOKEN")
            )
            props = parse_conf_lines(text.splitlines(True), d.dict)
        else:
            with open(path, "r", encoding="utf-8") as f:
                props = parse_conf_lines(f.readlines(), d.dict)
        merged = d.with_settings(props)
        cls.set_active_dictionary(merged)
        return merged
