"""Core primitives: config dictionary, schemas, columnar batches."""

from .config import SettingDictionary, SettingNamespace, parse_duration_seconds
from .confmanager import ConfigManager

__all__ = [
    "SettingDictionary",
    "SettingNamespace",
    "parse_duration_seconds",
    "ConfigManager",
]
