"""Secret resolution: ``keyvault://vault/name`` URIs in config values.

reference: datax-host securedsetting/KeyVaultClient.scala:19-130 — any
config *value* may be a ``keyvault://<vault>/<secret>`` URI and the engine
resolves it transparently (``resolveSecretIfAny`` applied to every value
read, :108-125); the C# side generates the same URIs at config-gen time
(DataX.Config.KeyVault). The vault itself is reached with MSI auth
(datax-keyvault/KeyVaultMsiAuthenticatorClient.scala).

TPU-native stand-in: vaults are local JSON files (``<root>/<vault>.json``
name->secret maps, the one-box analog of a cloud vault) with an
environment-variable overlay ``DATAX_SECRET_<VAULT>_<NAME>`` taking
precedence (the MSI-equivalent injection path under k8s: mount secrets
as env). A process-wide resolver keeps one cache, like the reference's
singleton client.
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Any, Dict, Optional

SECRET_URI_RE = re.compile(r"^(keyvault|secretscope|secret)://([^/]+)/(.+)$")

DEFAULT_VAULT_DIR_ENV = "DATAX_VAULT_DIR"


class SecretNotFound(KeyError):
    pass


class SecretVault:
    """Resolves secret URIs from env overlay + local vault files."""

    def __init__(self, vault_dir: Optional[str] = None):
        # default under $HOME, not /tmp: a world-writable default dir
        # would let any local user pre-seed secrets the config resolves
        self.vault_dir = vault_dir or os.environ.get(
            DEFAULT_VAULT_DIR_ENV, os.path.expanduser("~/.dxtpu/vault")
        )
        self._cache: Dict[str, Dict[str, str]] = {}
        self._lock = threading.Lock()

    def _env_key(self, vault: str, name: str) -> str:
        clean = lambda s: re.sub(r"[^A-Za-z0-9]", "_", s).upper()  # noqa: E731
        return f"DATAX_SECRET_{clean(vault)}_{clean(name)}"

    def _load_vault(self, vault: str) -> Dict[str, str]:
        with self._lock:
            if vault in self._cache:
                return self._cache[vault]
        path = os.path.join(self.vault_dir, f"{vault}.json")
        data: Dict[str, str] = {}
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                data = {str(k): str(v) for k, v in json.load(f).items()}
        with self._lock:
            self._cache[vault] = data
        return data

    def invalidate(self) -> None:
        with self._lock:
            self._cache.clear()

    def get_secret(self, vault: str, name: str) -> str:
        env = os.environ.get(self._env_key(vault, name))
        if env is not None:
            return env
        data = self._load_vault(vault)
        if name not in data:
            raise SecretNotFound(f"secret {name!r} not found in vault {vault!r}")
        return data[name]

    def set_secret(self, vault: str, name: str, value: str) -> str:
        """Write-through to the vault file; returns the canonical URI
        (the config-gen side mints URIs this way, DataX.Config.KeyVault).

        The vault dir/file get owner-only permissions — the local-file
        vault is only as private as its mode."""
        os.makedirs(self.vault_dir, mode=0o700, exist_ok=True)
        try:
            os.chmod(self.vault_dir, 0o700)
        except OSError:
            pass
        path = os.path.join(self.vault_dir, f"{vault}.json")
        data = dict(self._load_vault(vault))
        data[name] = value
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        with self._lock:
            self._cache[vault] = data
        return secret_uri(vault, name)

    # -- uri resolution ---------------------------------------------------
    def resolve_if_any(self, value: Any) -> Any:
        """Resolve a value if it is a secret URI, else return unchanged
        (KeyVaultClient.scala resolveSecretIfAny :108-125)."""
        if not isinstance(value, str):
            return value
        m = SECRET_URI_RE.match(value.strip())
        if not m:
            return value
        return self.get_secret(m.group(2), m.group(3))

    def resolve_deep(self, value: Any) -> Any:
        """Deep-resolve URIs in nested dict/list config structures."""
        if isinstance(value, dict):
            return {k: self.resolve_deep(v) for k, v in value.items()}
        if isinstance(value, list):
            return [self.resolve_deep(v) for v in value]
        return self.resolve_if_any(value)


def secret_uri(vault: str, name: str) -> str:
    return f"keyvault://{vault}/{name}"


def is_secret_uri(value: Any) -> bool:
    return isinstance(value, str) and bool(SECRET_URI_RE.match(value.strip()))


# process-wide resolver (reference keeps a singleton KeyVault client)
_DEFAULT: Optional[SecretVault] = None
_DEFAULT_LOCK = threading.Lock()


def default_vault() -> SecretVault:
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = SecretVault()
    return _DEFAULT


def reset_default_vault() -> None:
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None


def resolve_secret_if_any(value: Any) -> Any:
    return default_vault().resolve_if_any(value)
