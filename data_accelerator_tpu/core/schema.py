"""Schemas: Spark-style schema JSON -> flat typed column map.

Flow configs carry input schemas in Spark's JSON schema format
(e.g. HomeAutomationLocal.json ``inputSchemaFile``); we parse the same
format (reference: datax-host input/SchemaFile.scala loads it via Spark's
``DataType.fromJson``) but flatten nested structs into dotted column paths
— the device representation is struct-of-arrays, not row objects.

Column types on device (TPU-first, no x64):
- LONG    -> int32
- DOUBLE  -> float32
- BOOLEAN -> bool
- STRING  -> int32 dictionary id (host keeps the id<->str dictionary)
- TIMESTAMP -> int32 milliseconds relative to the batch's host-side
  ``base_ms`` (covers +-24 days per batch; absolute time is restored on
  the host at sink/metric boundaries)
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional

import numpy as np


class ColType(Enum):
    LONG = "long"
    DOUBLE = "double"
    BOOLEAN = "boolean"
    STRING = "string"
    TIMESTAMP = "timestamp"

    @property
    def np_dtype(self):
        return {
            ColType.LONG: np.int32,
            ColType.DOUBLE: np.float32,
            ColType.BOOLEAN: np.bool_,
            ColType.STRING: np.int32,
            ColType.TIMESTAMP: np.int32,
        }[self]


_SPARK_TYPE_MAP = {
    "long": ColType.LONG,
    "integer": ColType.LONG,
    "int": ColType.LONG,
    "short": ColType.LONG,
    "byte": ColType.LONG,
    "double": ColType.DOUBLE,
    "float": ColType.DOUBLE,
    "decimal": ColType.DOUBLE,
    "boolean": ColType.BOOLEAN,
    "string": ColType.STRING,
    "timestamp": ColType.TIMESTAMP,
    "date": ColType.TIMESTAMP,
}


@dataclass(frozen=True)
class Column:
    name: str  # dotted path, e.g. "deviceDetails.deviceId"
    ctype: ColType
    nullable: bool = True
    metadata: dict = field(default_factory=dict)


@dataclass(frozen=True)
class Schema:
    columns: List[Column]

    def __post_init__(self):
        names = [c.name for c in self.columns]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate column names in schema: {names}")

    @property
    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def column(self, name: str) -> Column:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(name)

    def has(self, name: str) -> bool:
        return any(c.name == name for c in self.columns)

    # -- Spark schema JSON ----------------------------------------------
    @staticmethod
    def from_spark_json(text_or_obj) -> "Schema":
        obj = (
            json.loads(text_or_obj) if isinstance(text_or_obj, str) else text_or_obj
        )
        cols: List[Column] = []

        def walk(fields: list, prefix: str) -> None:
            for f in fields:
                name = prefix + f["name"]
                ftype = f.get("type", "string")
                if isinstance(ftype, dict) and ftype.get("type") == "struct":
                    walk(ftype["fields"], name + ".")
                    continue
                if isinstance(ftype, dict):
                    raise ValueError(
                        f"unsupported nested type for column {name}: {ftype.get('type')}"
                    )
                base = str(ftype).lower()
                if base.startswith("decimal"):
                    base = "decimal"
                if base not in _SPARK_TYPE_MAP:
                    raise ValueError(f"unsupported column type {ftype!r} for {name}")
                metadata = f.get("metadata") or {}
                ctype = _SPARK_TYPE_MAP[base]
                # long columns carrying epoch millis (marked
                # useCurrentTimeMillis, e.g. HomeAutomationLocal's
                # deviceDetails.eventTime) don't fit int32 — treat them as
                # TIMESTAMP so they get the relative-ms device encoding
                if ctype == ColType.LONG and metadata.get("useCurrentTimeMillis"):
                    ctype = ColType.TIMESTAMP
                cols.append(
                    Column(
                        name=name,
                        ctype=ctype,
                        nullable=bool(f.get("nullable", True)),
                        metadata=metadata,
                    )
                )

        if obj.get("type") != "struct":
            raise ValueError("schema root must be a struct")
        walk(obj.get("fields", []), "")
        return Schema(cols)

    def to_spark_json(self) -> dict:
        """Serialize back to (flattened) Spark schema JSON."""
        return {
            "type": "struct",
            "fields": [
                {
                    "name": c.name,
                    "type": c.ctype.value,
                    "nullable": c.nullable,
                    "metadata": c.metadata,
                }
                for c in self.columns
            ],
        }


class DictionaryFullError(RuntimeError):
    """Raised in strict mode when the string dictionary hits its
    configured capacity bound."""


class StringDictionary:
    """Host-side bidirectional string<->int32 id dictionary.

    One shared dictionary per job keeps ids stable across batches and
    columns, so device-side equality/grouping/joins on dictionary ids are
    exact string semantics (no hashing collisions). id 0 is reserved for
    null/missing.
    """

    NULL_ID = 0

    def __init__(self, max_size: Optional[int] = None, strict: bool = False):
        import threading

        self._to_id: Dict[str, int] = {}
        self._to_str: List[Optional[str]] = [None]  # id 0 -> null
        # encode is check-then-append: the decode-ahead ingest worker
        # and the main thread's aux-table build both insert, so the
        # write path must be serialized (reads stay lock-free — CPython
        # list/dict reads see a consistent prefix)
        self._write_lock = threading.Lock()
        # optional capacity bound (conf process.stringdictionary.maxsize):
        # a hostile/high-cardinality stream would otherwise grow the
        # dictionary — and every device lookup table derived from it —
        # without limit. Beyond the bound new strings encode to NULL and
        # are counted (overflow_count -> an ingest metric), or raise in
        # strict mode. Existing ids are never evicted: device state
        # (rings, state tables) holds ids across batches, so eviction
        # would corrupt history.
        self.max_size = max_size
        self.strict = strict
        self.overflow_count = 0

    def __len__(self) -> int:
        return len(self._to_str)

    def encode(self, s: Optional[str]) -> int:
        if s is None:
            return self.NULL_ID
        i = self._to_id.get(s)
        if i is not None:
            return i
        with self._write_lock:
            i = self._to_id.get(s)  # racer may have inserted it
            if i is not None:
                return i
            if self.max_size is not None and len(self._to_str) >= self.max_size:
                if self.strict:
                    raise DictionaryFullError(
                        f"string dictionary reached its configured bound "
                        f"({self.max_size}); new string {s!r} rejected "
                        "(datax.job.process.stringdictionary.strict=true)"
                    )
                self.overflow_count += 1
                return self.NULL_ID
            i = len(self._to_str)
            self._to_str.append(s)
            self._to_id[s] = i
            return i

    def entries(self) -> List[str]:
        """Every non-null entry in id order (id 1 first) — the snapshot
        a checkpoint persists so device-resident ids survive restarts."""
        return list(self._to_str[1:])

    def restore_entries(self, saved: List[str]) -> bool:
        """Replay a saved ``entries()`` list into this dictionary.

        The current contents (strings encoded during flow compile) must
        be a prefix of the saved list — same conf produces the same
        compile-time encodes in the same order — otherwise the saved ids
        would alias different strings and the restore is refused.

        Replay bypasses the capacity bound: these entries ARE prior
        state (device rings reference their ids), so an operator who
        lowered ``maxsize`` below the saved size must still get an exact
        restore — the bound applies to NEW strings only."""
        with self._write_lock:
            current = self._to_str[1:]
            if current != saved[: len(current)]:
                return False
            for s in saved[len(current):]:
                self._to_id[s] = len(self._to_str)
                self._to_str.append(s)
            return True

    def lookup(self, s: Optional[str]) -> int:
        """Encode without inserting; unseen strings get -1 (matches nothing)."""
        if s is None:
            return self.NULL_ID
        return self._to_id.get(s, -1)

    def decode(self, i: int) -> Optional[str]:
        if 0 <= i < len(self._to_str):
            return self._to_str[i]
        return None

    def decode_array(self, ids) -> List[Optional[str]]:
        return [self.decode(int(i)) for i in np.asarray(ids)]
