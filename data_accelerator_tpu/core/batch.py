"""Columnar micro-batch: the device-resident unit of streaming data.

Where the reference engine's unit is a Spark ``DataFrame`` of rows, the
TPU-native unit is a fixed-capacity struct-of-arrays with a validity mask.
Static shapes are what let XLA compile the whole flow pipeline once and
reuse it every batch (reference hot path analog:
CommonProcessorFactory.scala:333-399 processDataset).

A ``Batch`` is a registered pytree: column arrays + validity mask + the
scalar ``base_ms`` are traced leaves; the column ordering is static
structure. String columns hold int32 dictionary ids (see
``core.schema.StringDictionary``); timestamps are int32 ms since
``base_ms``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .schema import ColType, Column, Schema, StringDictionary


@jax.tree_util.register_pytree_node_class
@dataclass
class Batch:
    """Fixed-capacity columnar batch.

    columns: name -> [capacity] array (int32/float32/bool)
    valid:   [capacity] bool mask of live rows
    base_ms: scalar int64-on-host epoch-ms origin for TIMESTAMP columns,
             carried as a traced float32 scalar (seconds precision is
             enough for window/bookkeeping math on device).
    """

    columns: Dict[str, jnp.ndarray]
    valid: jnp.ndarray
    base_ms: jnp.ndarray  # scalar float32: epoch seconds of the batch origin

    def tree_flatten(self):
        names = tuple(sorted(self.columns))
        children = tuple(self.columns[n] for n in names) + (self.valid, self.base_ms)
        return children, names

    @classmethod
    def tree_unflatten(cls, names, children):
        cols = dict(zip(names, children[: len(names)]))
        valid, base_ms = children[len(names)], children[len(names) + 1]
        return cls(cols, valid, base_ms)

    # -- basic props -----------------------------------------------------
    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])

    @property
    def names(self) -> List[str]:
        return list(self.columns)

    def count(self) -> jnp.ndarray:
        """Number of live rows (traced scalar)."""
        return jnp.sum(self.valid.astype(jnp.int32))

    def with_columns(self, columns: Dict[str, jnp.ndarray]) -> "Batch":
        return Batch(columns, self.valid, self.base_ms)

    def with_valid(self, valid: jnp.ndarray) -> "Batch":
        return Batch(self.columns, valid, self.base_ms)

    def select(self, names: Sequence[str]) -> "Batch":
        return self.with_columns({n: self.columns[n] for n in names})


def empty_batch(schema: Schema, capacity: int, base_ms: float = 0.0) -> Batch:
    cols = {
        c.name: jnp.zeros((capacity,), dtype=c.ctype.np_dtype) for c in schema.columns
    }
    return Batch(
        cols,
        jnp.zeros((capacity,), dtype=jnp.bool_),
        jnp.asarray(base_ms / 1000.0, dtype=jnp.float32),
    )


def batch_from_rows(
    rows: List[dict],
    schema: Schema,
    capacity: int,
    dictionary: StringDictionary,
    base_ms: Optional[int] = None,
    stats: Optional[Dict[str, int]] = None,
) -> Batch:
    """Host-side encode of JSON-like row dicts into a device batch.

    Nested dicts are addressed by the schema's dotted paths. Rows beyond
    ``capacity`` are dropped (the runtime's ingest chunker prevents this).
    This is the pure-Python fallback path; the C++ decoder in
    ``native/`` produces the same buffers for the hot ingest path.

    A row whose TIMESTAMP column holds an unparseable string is marked
    invalid (not silently anchored at the batch base time); pass
    ``stats`` to receive a ``bad_timestamps`` count for metrics.
    """
    n = min(len(rows), capacity)
    bad_ts = np.zeros((capacity,), dtype=np.bool_)
    if base_ms is None:
        base_ms = 0
        for r in rows[:n]:
            ts = _first_timestamp(r, schema)
            if ts is not None:
                base_ms = ts
                break

    arrays: Dict[str, np.ndarray] = {}
    for col in schema.columns:
        arr = np.zeros((capacity,), dtype=col.ctype.np_dtype)
        for i in range(n):
            v = _dig(rows[i], col.name)
            if v is None:
                continue
            if col.ctype == ColType.STRING:
                arr[i] = dictionary.encode(str(v))
            elif col.ctype == ColType.TIMESTAMP:
                if isinstance(v, str):
                    # string timestamps parse at the encode boundary —
                    # the role of the reference's stringToTimestamp
                    # built-in UDF (BuiltInFunctionsHandler); device
                    # columns never hold raw date strings
                    v = parse_timestamp_ms(v)
                    if v is None:
                        # garbage timestamp: excluding the row beats
                        # silently treating it as the batch base time
                        # (which would window it wrongly)
                        bad_ts[i] = True
                        continue
                # relative ms saturate at the int32 range: a sample/replay
                # row weeks away from the batch base clamps (~±24 days)
                # instead of overflowing
                arr[i] = np.int32(
                    max(-2**31, min(2**31 - 1, int(v) - base_ms))
                )
            elif col.ctype == ColType.BOOLEAN:
                arr[i] = bool(v)
            elif col.ctype == ColType.LONG:
                arr[i] = np.int32(int(v))
            else:
                arr[i] = np.float32(v)
        arrays[col.name] = arr

    valid = np.zeros((capacity,), dtype=np.bool_)
    valid[:n] = True
    valid &= ~bad_ts
    if stats is not None:
        stats["bad_timestamps"] = (
            stats.get("bad_timestamps", 0) + int(bad_ts.sum())
        )
    return Batch(
        {k: jnp.asarray(v) for k, v in arrays.items()},
        jnp.asarray(valid),
        jnp.asarray(base_ms / 1000.0, dtype=jnp.float32),
    )


def batch_to_rows(
    batch: Batch,
    dictionary: StringDictionary,
    schema_types: Optional[Dict[str, ColType]] = None,
    max_rows: Optional[int] = None,
) -> List[dict]:
    """Device -> host rows (only valid rows), decoding dictionary ids and
    restoring absolute timestamps. Used by sinks and LiveQuery display."""
    host_cols = {k: np.asarray(v) for k, v in batch.columns.items()}
    valid = np.asarray(batch.valid)
    base_ms = int(round(float(np.asarray(batch.base_ms)) * 1000.0))
    idx = np.nonzero(valid)[0]
    if max_rows is not None:
        idx = idx[:max_rows]
    types = schema_types or {}
    out = []
    for i in idx:
        row = {}
        for name, arr in host_cols.items():
            v = arr[i]
            ctype = types.get(name)
            if ctype == ColType.STRING:
                row[name] = dictionary.decode(int(v))
            elif ctype == ColType.TIMESTAMP:
                row[name] = int(v) + base_ms
            elif arr.dtype == np.bool_:
                row[name] = bool(v)
            elif np.issubdtype(arr.dtype, np.integer):
                row[name] = int(v)
            else:
                row[name] = float(v)
        out.append(row)
    return out


def _dig(obj: dict, dotted: str):
    cur = obj
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def parse_timestamp_ms(text: str) -> Optional[int]:
    """Parse a timestamp string to epoch ms (stringToTimestamp role).

    Accepts ISO-8601 (T or space separator, optional fraction/Z) and
    bare epoch seconds/millis digits; returns None on garbage."""
    from datetime import datetime, timezone

    s = text.strip()
    if not s:
        return None
    if s.replace(".", "", 1).isdigit():
        num = float(s)
        return int(num if num > 1e12 else num * 1000.0)
    try:
        t = datetime.fromisoformat(s.replace("Z", "+00:00").replace(" ", "T"))
    except ValueError:
        return None
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    return int(t.timestamp() * 1000)


def _first_timestamp(row: dict, schema: Schema) -> Optional[int]:
    for col in schema.columns:
        if col.ctype == ColType.TIMESTAMP:
            v = _dig(row, col.name)
            if isinstance(v, str):
                v = parse_timestamp_ms(v)  # unparseable -> fall through
            if v is not None:
                return int(v)
    return None
