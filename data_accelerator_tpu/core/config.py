"""Flat ``datax.job.*`` configuration dictionary with namespace grouping.

A job's entire feature set is switched on/off purely by presence of keys in
one flat string->string map — the same contract as the reference engine, so
flattened configs produced for the reference remain readable here.

reference: datax-core SettingDictionary.scala:20-150, SettingNamespace.scala:9-48
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, Optional, TypeVar

from ..constants import JobArgument, ProductConstant

T = TypeVar("T")


class EngineException(Exception):
    """Engine-level configuration/processing error (reference: EngineException.scala)."""


class SettingNamespace:
    """Well-known namespace prefixes. reference: SettingNamespace.scala:9-48"""

    DefaultSettingName = ""
    Separator = "."
    ValueSeparator = ";"
    Root = ProductConstant.ProductRoot  # "datax"
    RootPrefix = Root + Separator
    Job = "job"
    JobPrefix = RootPrefix + Job + Separator  # "datax.job."

    JobName = "name"
    JobNameFullPath = JobPrefix + JobName

    JobInput = "input.default"
    JobInputPrefix = JobPrefix + JobInput + Separator

    JobProcess = "process"
    JobProcessPrefix = JobPrefix + JobProcess + Separator

    JobOutput = "output"
    JobOutputPrefix = JobPrefix + JobOutput + Separator

    @staticmethod
    def build_setting_path(*names: Optional[str]) -> str:
        return SettingNamespace.Separator.join(n for n in names if n is not None)

    @staticmethod
    def get_sub_namespace(prop_name: str, start_index: int) -> Optional[str]:
        """First namespace component of ``prop_name`` after ``start_index``.

        reference: SettingNamespace.scala:37-47
        """
        if len(prop_name) > start_index:
            pos = prop_name.find(SettingNamespace.Separator, start_index)
            if pos >= 0:
                return prop_name[start_index:pos]
            return prop_name[start_index:]
        return None


_DURATION_UNITS = {
    "d": 86400.0, "day": 86400.0, "days": 86400.0,
    "h": 3600.0, "hour": 3600.0, "hours": 3600.0,
    "m": 60.0, "min": 60.0, "mins": 60.0, "minute": 60.0, "minutes": 60.0,
    "s": 1.0, "sec": 1.0, "secs": 1.0, "second": 1.0, "seconds": 1.0,
    "ms": 1e-3, "milli": 1e-3, "millis": 1e-3,
    "millisecond": 1e-3, "milliseconds": 1e-3,
    "us": 1e-6, "micro": 1e-6, "micros": 1e-6,
    "microsecond": 1e-6, "microseconds": 1e-6,
    "ns": 1e-9, "nano": 1e-9, "nanos": 1e-9,
    "nanosecond": 1e-9, "nanoseconds": 1e-9,
}

_DURATION_RE = re.compile(r"^\s*([+-]?\d+(?:\.\d+)?)\s*([a-zA-Z]*)\s*$")


def parse_duration_seconds(text: str) -> float:
    """Parse durations like ``"5 minutes"``, ``"0 second"``, ``"60"`` (secs).

    Matches the scala ``Duration.create`` strings used throughout flow
    configs (reference: SettingDictionary.scala:45-46, TimeWindowHandler
    reading ``process.timewindow.*`` / ``watermark``).
    """
    m = _DURATION_RE.match(text)
    if not m:
        raise EngineException(f"cannot parse duration: {text!r}")
    value = float(m.group(1))
    unit = m.group(2).lower()
    if unit == "":
        return value  # bare number: seconds
    if unit not in _DURATION_UNITS:
        raise EngineException(f"unknown duration unit in {text!r}")
    return value * _DURATION_UNITS[unit]


@dataclass(frozen=True)
class SettingDictionary:
    """Immutable flat string map with namespace-aware accessors.

    reference: SettingDictionary.scala:20-150
    """

    elems: Dict[str, str] = field(default_factory=dict)
    parent_prefix: str = SettingNamespace.DefaultSettingName

    # -- plain accessors -------------------------------------------------
    @property
    def dict(self) -> Dict[str, str]:
        return self.elems

    def __len__(self) -> int:
        return len(self.elems)

    @staticmethod
    def _resolve(value: Optional[str]) -> Optional[str]:
        """Transparent ``keyvault://vault/name`` resolution on read
        (reference: KeyVaultClient.scala:108-125 resolveSecretIfAny is
        applied to every config value the engine reads)."""
        if value is None or "://" not in value:
            return value
        from .secrets import resolve_secret_if_any

        return resolve_secret_if_any(value)

    def get(self, key: str) -> Optional[str]:
        return self._resolve(self.elems.get(key))

    def get_default(self) -> Optional[str]:
        return self._resolve(self.elems.get(SettingNamespace.DefaultSettingName))

    def _get_or_throw(self, value: Optional[T], key: str) -> T:
        if value is None:
            raise EngineException(
                f"config setting '{self.parent_prefix + key}' is not found"
            )
        return value

    def get_string(self, key: str) -> str:
        return self._get_or_throw(self._resolve(self.elems.get(key)), key)

    def get_or_else(self, key: str, default: Optional[str]) -> Optional[str]:
        v = self._resolve(self.elems.get(key))
        return default if v is None else v

    def get_int_option(self, key: str) -> Optional[int]:
        v = self.elems.get(key)
        return None if v is None else int(v)

    def get_long_option(self, key: str) -> Optional[int]:
        return self.get_int_option(key)

    def get_long(self, key: str) -> int:
        return self._get_or_throw(self.get_int_option(key), key)

    def get_double_option(self, key: str) -> Optional[float]:
        v = self.elems.get(key)
        return None if v is None else float(v)

    def get_double(self, key: str) -> float:
        return self._get_or_throw(self.get_double_option(key), key)

    def get_bool_option(self, key: str) -> Optional[bool]:
        v = self.elems.get(key)
        if v is None:
            return None
        lowered = v.strip().lower()
        if lowered in ("true", "1", "yes"):
            return True
        if lowered in ("false", "0", "no"):
            return False
        raise EngineException(f"cannot parse boolean setting {key}={v!r}")

    def get_duration_option(self, key: str) -> Optional[float]:
        """Duration in (float) seconds."""
        v = self.elems.get(key)
        return None if v is None else parse_duration_seconds(v)

    def get_duration(self, key: str) -> float:
        return self._get_or_throw(self.get_duration_option(key), key)

    def get_string_seq_option(self, key: str) -> Optional[list]:
        v = self.elems.get(key)
        if v is None:
            return None
        seq = [s for s in v.split(SettingNamespace.ValueSeparator) if s]
        return seq if seq else None

    # -- namespace operations -------------------------------------------
    def _find_with_prefix(self, prefix: str) -> Dict[str, str]:
        return {k: v for k, v in self.elems.items() if k.startswith(prefix)}

    @staticmethod
    def _strip_keys(d: Dict[str, str], start: int) -> Dict[str, str]:
        return {k[start:]: v for k, v in d.items() if k is not None and len(k) > start}

    @staticmethod
    def _strip_keys_by_namespace(d: Dict[str, str], namespace: str) -> Dict[str, str]:
        # a key equal to the namespace itself becomes the "" default setting
        # (reference: SettingDictionary.scala:59-67)
        prefix_len = len(namespace + SettingNamespace.Separator)
        out: Dict[str, str] = {}
        for k, v in d.items():
            if k is None or len(k) < len(namespace):
                continue
            if k == namespace:
                out[SettingNamespace.DefaultSettingName] = v
            else:
                out[k[prefix_len:]] = v
        return out

    def group_by_sub_namespace(
        self, prefix: Optional[str] = None
    ) -> Dict[str, "SettingDictionary"]:
        """Strip ``prefix`` and group remaining keys by first namespace part.

        reference: SettingDictionary.scala:77-86
        """
        if not prefix:
            sub = dict(self.elems)
        else:
            sub = self._strip_keys(self._find_with_prefix(prefix), len(prefix))

        groups: Dict[str, Dict[str, str]] = {}
        for k, v in sub.items():
            ns = SettingNamespace.get_sub_namespace(k, 0)
            if ns is None:
                continue
            groups.setdefault(ns, {})[k] = v

        return {
            ns: SettingDictionary(
                self._strip_keys_by_namespace(kv, ns),
                self.parent_prefix + (prefix or "") + ns + SettingNamespace.Separator,
            )
            for ns, kv in groups.items()
        }

    def get_sub_dictionary(self, prefix: str) -> "SettingDictionary":
        """reference: SettingDictionary.scala:93-95"""
        return SettingDictionary(
            self._strip_keys(self._find_with_prefix(prefix), len(prefix)),
            self.parent_prefix + prefix,
        )

    def build_config_map(
        self,
        builder: Callable[["SettingDictionary", str], T],
        prefix: Optional[str] = None,
    ) -> Dict[str, T]:
        """reference: SettingDictionary.scala:102-105"""
        return {
            k: builder(v, k) for k, v in self.group_by_sub_namespace(prefix).items()
        }

    # -- well-known settings --------------------------------------------
    def get_app_name(self) -> str:
        return self.elems.get(
            JobArgument.ConfName_AppName, ProductConstant.DefaultAppName
        )

    def get_job_name(self) -> str:
        return self.elems.get(SettingNamespace.JobNameFullPath, self.get_app_name())

    def get_metric_app_name(self) -> str:
        return ProductConstant.MetricAppNamePrefix + self.get_job_name()

    def get_app_configuration_file(self) -> Optional[str]:
        return self.elems.get(JobArgument.ConfName_AppConf)

    def with_settings(self, extra: Dict[str, str]) -> "SettingDictionary":
        merged = dict(self.elems)
        merged.update(extra)
        return SettingDictionary(merged, self.parent_prefix)


def parse_conf_lines(
    lines: Iterable[str], replacements: Optional[Dict[str, str]] = None
) -> Dict[str, str]:
    """Parse flat ``key=value`` conf lines with ``${token}`` replacement.

    reference: ConfigManager.scala:98-135
    """
    out: Dict[str, str] = {}
    for line in lines:
        if line is None:
            continue
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        pos = stripped.find("=")
        if pos == 0:
            key, value = "", stripped
        elif pos > 0:
            key, value = stripped[:pos].strip(), stripped[pos + 1:].strip()
        else:
            # flag-only line: store empty string so the key still registers
            # as present (the reference keeps the key with a null value;
            # features are switched purely by key presence)
            key, value = stripped, ""
        out[key] = replace_tokens(_unescape_value(value), replacements)
    return out


def _unescape_value(value: str) -> str:
    """java-properties-style escapes: multi-line values (projection steps,
    inline snippets) are written as literal ``\\n`` in the flat .conf the
    flattener produces; ``\\\\`` preserves literal backslashes (regexes,
    Windows paths)."""
    if "\\" not in value:
        return value
    out = []
    i, n = 0, len(value)
    while i < n:
        ch = value[i]
        if ch == "\\" and i + 1 < n:
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt == "t":
                out.append("\t")
                i += 2
                continue
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def replace_tokens(src: Optional[str], tokens: Optional[Dict[str, str]]) -> Optional[str]:
    """Literal ``${name}`` substitution. reference: ConfigManager.scala:83-88"""
    if not tokens or src is None or src == "":
        return src
    for name, value in tokens.items():
        if value is not None:
            src = src.replace("${" + name + "}", value)
    return src
