"""Capacity-bounded inner/left equi-join.

Replaces Spark's shuffle-hash/broadcast join (implicit in spark.sql for
the reference's JOIN queries, e.g. refdata joins in
HomeAutomationLocal.json) with a static-shape pairwise-match formulation:
build the [n, m] match matrix — an outer comparison the VPU chews through
— then extract matching (left, right) index pairs with a fixed output
capacity via ``jnp.nonzero(size=...)``.

This favors the flows' actual join shapes (small-to-medium right sides:
reference data, per-window aggregates). For large-x-large joins the
``parallel`` layer shards the left side across devices so each chip holds
an [n/d, m] tile.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax.numpy as jnp


def inner_join_indices(
    left_keys,
    right_keys,
    left_valid: jnp.ndarray,
    right_valid: jnp.ndarray,
    out_capacity: int,
    residual: Optional[Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Return (left_idx[out], right_idx[out], valid[out], dropped) of
    matching pairs.

    left_keys/right_keys: sequences of [n] / [m] arrays (conjunctive
    equality). ``residual``: optional extra predicate evaluated pairwise on
    (left_row_idx_matrix, right_row_idx_matrix) -> [n, m] bool, for
    non-equi ON terms.

    Pairs beyond ``out_capacity`` are dropped; ``dropped`` (scalar int32)
    counts them, and the planner rides it through to the runtime so the
    flow emits an ``Output_<n>_JoinRowsDropped`` metric rather than
    failing, matching at-least-once streaming semantics.
    """
    n = left_valid.shape[0]
    m = right_valid.shape[0]
    match = left_valid[:, None] & right_valid[None, :]
    for lk, rk in zip(left_keys, right_keys):
        match = match & (lk[:, None] == rk[None, :])
    if residual is not None:
        li = jnp.broadcast_to(jnp.arange(n)[:, None], (n, m))
        ri = jnp.broadcast_to(jnp.arange(m)[None, :], (n, m))
        match = match & residual(li, ri)

    flat = match.reshape(-1)
    total = jnp.sum(flat.astype(jnp.int32))
    dropped = jnp.maximum(total - jnp.int32(out_capacity), 0)
    (pair_idx,) = jnp.nonzero(flat, size=out_capacity, fill_value=-1)
    valid = pair_idx >= 0
    pair_idx = jnp.where(valid, pair_idx, 0)
    left_idx = pair_idx // m
    right_idx = pair_idx % m
    return left_idx, right_idx, valid, dropped


def left_join_indices(
    left_keys,
    right_keys,
    left_valid: jnp.ndarray,
    right_valid: jnp.ndarray,
    out_capacity: int,
    residual=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """LEFT OUTER variant: also emits unmatched left rows once.

    Returns (left_idx, right_idx, valid, right_is_null, dropped): where
    ``right_is_null`` marks rows whose right side carries no match (their
    right columns must be nulled by the caller) and ``dropped`` (scalar
    int32) counts output rows lost to the capacity bound.
    """
    n = left_valid.shape[0]
    m = right_valid.shape[0]
    match = left_valid[:, None] & right_valid[None, :]
    for lk, rk in zip(left_keys, right_keys):
        match = match & (lk[:, None] == rk[None, :])
    if residual is not None:
        li = jnp.broadcast_to(jnp.arange(n)[:, None], (n, m))
        ri = jnp.broadcast_to(jnp.arange(m)[None, :], (n, m))
        match = match & residual(li, ri)

    has_match = jnp.any(match, axis=1)
    unmatched = left_valid & ~has_match
    # matched pairs followed by unmatched-left singles, in one index space:
    # pair space [n*m] then singles space [n]
    flat = jnp.concatenate([match.reshape(-1), unmatched])
    total = jnp.sum(flat.astype(jnp.int32))
    dropped = jnp.maximum(total - jnp.int32(out_capacity), 0)
    (idx,) = jnp.nonzero(flat, size=out_capacity, fill_value=-1)
    valid = idx >= 0
    idx = jnp.where(valid, idx, 0)
    is_single = idx >= n * m
    pair_idx = jnp.where(is_single, 0, idx)
    left_idx = jnp.where(is_single, idx - n * m, pair_idx // m)
    right_idx = jnp.where(is_single, 0, pair_idx % m)
    return left_idx, right_idx, valid, is_single, dropped
