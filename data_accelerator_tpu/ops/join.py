"""Capacity-bounded inner/left equi-join.

Replaces Spark's shuffle-hash/broadcast join (implicit in spark.sql for
the reference's JOIN queries, e.g. refdata joins in
HomeAutomationLocal.json) with two static-shape formulations the
planner chooses between per join site (shapes are static, so the
choice is compile-time):

- **sort-merge** (``sort_join_indices``, the default for pure equi
  joins): dense group ids over the UNION of both sides' key tuples
  (one lexsort), then searchsorted range lookup per left row and a
  searchsorted-over-cumsum expansion into the fixed output capacity —
  O((n+m+cap)·log). This is what keeps current-batch x windowed-table
  joins (BASELINE config 3: 8k x 100k and beyond) off the O(n·m)
  cliff.
- **match-matrix** (``inner_join_indices``/``left_join_indices``):
  the [n, m] outer comparison, kept for joins with non-equi residual
  ON terms, which need the full pair mask anyway.

Pair output order is identical between the two (left-major, right in
original index order — stable sorts keep equal-gid rows in input
order), so the planner can switch freely.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax.numpy as jnp

from .groupby import group_ids


def inner_join_indices(
    left_keys,
    right_keys,
    left_valid: jnp.ndarray,
    right_valid: jnp.ndarray,
    out_capacity: int,
    residual: Optional[Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Return (left_idx[out], right_idx[out], valid[out], dropped) of
    matching pairs.

    left_keys/right_keys: sequences of [n] / [m] arrays (conjunctive
    equality). ``residual``: optional extra predicate evaluated pairwise on
    (left_row_idx_matrix, right_row_idx_matrix) -> [n, m] bool, for
    non-equi ON terms.

    Pairs beyond ``out_capacity`` are dropped; ``dropped`` (scalar int32)
    counts them, and the planner rides it through to the runtime so the
    flow emits an ``Output_<n>_JoinRowsDropped`` metric rather than
    failing, matching at-least-once streaming semantics.
    """
    n = left_valid.shape[0]
    m = right_valid.shape[0]
    match = left_valid[:, None] & right_valid[None, :]
    for lk, rk in zip(left_keys, right_keys):
        match = match & (lk[:, None] == rk[None, :])
    if residual is not None:
        li = jnp.broadcast_to(jnp.arange(n)[:, None], (n, m))
        ri = jnp.broadcast_to(jnp.arange(m)[None, :], (n, m))
        match = match & residual(li, ri)

    flat = match.reshape(-1)
    total = jnp.sum(flat.astype(jnp.int32))
    dropped = jnp.maximum(total - jnp.int32(out_capacity), 0)
    (pair_idx,) = jnp.nonzero(flat, size=out_capacity, fill_value=-1)
    valid = pair_idx >= 0
    pair_idx = jnp.where(valid, pair_idx, 0)
    left_idx = pair_idx // m
    right_idx = pair_idx % m
    return left_idx, right_idx, valid, dropped


def _union_gids(left_keys, right_keys, left_valid, right_valid):
    """Dense key-tuple ids across both sides: equal tuples (any mix of
    key columns/types) get equal ids; invalid rows get per-side
    sentinels that never match anything."""
    keys = [
        jnp.concatenate([lk, rk]) for lk, rk in zip(left_keys, right_keys)
    ]
    valid = jnp.concatenate([left_valid, right_valid])
    order, seg, _num, _first = group_ids(keys, valid)
    gid = jnp.zeros(valid.shape[0], jnp.int32).at[order].set(seg)
    n = left_valid.shape[0]
    gl = jnp.where(left_valid, gid[:n], -1)
    gr = jnp.where(right_valid, gid[n:], -2)
    return gl, gr


def sort_join_indices(
    left_keys,
    right_keys,
    left_valid: jnp.ndarray,
    right_valid: jnp.ndarray,
    out_capacity: int,
    left_outer: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sort-merge equi-join (no residual support — the planner keeps
    the match-matrix for those).

    Returns (left_idx, right_idx, valid, right_is_null, dropped) —
    the LEFT OUTER surface; for inner joins ``right_is_null`` is all
    False. Per left row: its matching right rows occupy a contiguous
    range of the gid-sorted right side, located with two searchsorteds;
    output slots map back to (left row, offset) via a searchsorted over
    the inclusive pair-count cumsum.
    """
    n = left_valid.shape[0]
    m = right_valid.shape[0]
    gl, gr = _union_gids(left_keys, right_keys, left_valid, right_valid)
    r_order = jnp.argsort(gr, stable=True)
    gr_s = gr[r_order]
    lo = jnp.searchsorted(gr_s, gl, side="left")
    hi = jnp.searchsorted(gr_s, gl, side="right")
    matches = jnp.where(left_valid, hi - lo, 0)
    if left_outer:
        # unmatched valid left rows emit one null-right row
        cnt = jnp.where(left_valid, jnp.maximum(matches, 1), 0)
    else:
        cnt = matches
    cum = jnp.cumsum(cnt)
    total = cum[-1]
    starts = cum - cnt
    j = jnp.arange(out_capacity)
    li = jnp.searchsorted(cum, j, side="right")
    valid_out = j < total
    li_c = jnp.clip(li, 0, n - 1)
    offset = j - starts[li_c]
    is_null = left_outer & (matches[li_c] == 0) & valid_out
    rpos = jnp.clip(lo[li_c] + offset, 0, m - 1)
    ri = r_order[rpos]
    dropped = jnp.maximum(total - jnp.int32(out_capacity), 0)
    left_idx = jnp.where(valid_out, li_c, 0)
    right_idx = jnp.where(valid_out & ~is_null, ri, 0)
    return left_idx, right_idx, valid_out, is_null, dropped


def left_join_indices(
    left_keys,
    right_keys,
    left_valid: jnp.ndarray,
    right_valid: jnp.ndarray,
    out_capacity: int,
    residual=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """LEFT OUTER variant: also emits unmatched left rows once.

    Returns (left_idx, right_idx, valid, right_is_null, dropped): where
    ``right_is_null`` marks rows whose right side carries no match (their
    right columns must be nulled by the caller) and ``dropped`` (scalar
    int32) counts output rows lost to the capacity bound.
    """
    n = left_valid.shape[0]
    m = right_valid.shape[0]
    match = left_valid[:, None] & right_valid[None, :]
    for lk, rk in zip(left_keys, right_keys):
        match = match & (lk[:, None] == rk[None, :])
    if residual is not None:
        li = jnp.broadcast_to(jnp.arange(n)[:, None], (n, m))
        ri = jnp.broadcast_to(jnp.arange(m)[None, :], (n, m))
        match = match & residual(li, ri)

    has_match = jnp.any(match, axis=1)
    unmatched = left_valid & ~has_match
    # matched pairs followed by unmatched-left singles, in one index space:
    # pair space [n*m] then singles space [n]
    flat = jnp.concatenate([match.reshape(-1), unmatched])
    total = jnp.sum(flat.astype(jnp.int32))
    dropped = jnp.maximum(total - jnp.int32(out_capacity), 0)
    (idx,) = jnp.nonzero(flat, size=out_capacity, fill_value=-1)
    valid = idx >= 0
    idx = jnp.where(valid, idx, 0)
    is_single = idx >= n * m
    pair_idx = jnp.where(is_single, 0, idx)
    left_idx = jnp.where(is_single, idx - n * m, pair_idx // m)
    right_idx = jnp.where(is_single, 0, pair_idx % m)
    return left_idx, right_idx, valid, is_single, dropped
