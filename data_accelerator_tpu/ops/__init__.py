"""XLA/Pallas kernels over columnar batches.

These take the role Spark's execution engine plays for the reference
(shuffle/aggregate/join inside ``spark.sql`` — CommonProcessorFactory.
scala:249-293): static-shape, mask-aware primitives that XLA fuses and
tiles onto the VPU/MXU.
"""

from .groupby import group_ids, segment_aggregate, distinct_mask
from .join import inner_join_indices
from .compact import compact_indices

__all__ = [
    "group_ids",
    "segment_aggregate",
    "distinct_mask",
    "inner_join_indices",
    "compact_indices",
]
