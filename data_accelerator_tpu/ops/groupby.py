"""Sort-based GROUP BY for fixed-capacity masked batches.

Replaces Spark's hash-exchange + aggregate for ``GROUP BY`` queries
(reference: implicit in spark.sql, CommonProcessorFactory.scala:257) with
an XLA-friendly static-shape pipeline:

  1. lexsort rows by (invalid-last, key columns)
  2. flag segment boundaries, prefix-sum into dense group ids
  3. ``jax.ops.segment_*`` reductions into a capacity-sized output

All shapes are static; invalid rows sort to the end and land in a dummy
trailing segment that the output mask hides. Group count <= row count, so
output capacity == input capacity is always sufficient.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp


def _as_sortable(col: jnp.ndarray) -> jnp.ndarray:
    """Make a column usable as a lexsort key (bool/float -> int bits)."""
    if col.dtype == jnp.bool_:
        return col.astype(jnp.int32)
    if jnp.issubdtype(col.dtype, jnp.floating):
        # total order on floats via sign-magnitude bit trick
        bits = jax.lax.bitcast_convert_type(col.astype(jnp.float32), jnp.int32)
        return jnp.where(bits < 0, jnp.int32(-2147483648) - bits, bits)
    return col.astype(jnp.int32)


def group_ids(
    keys: Sequence[jnp.ndarray], valid: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Compute dense group ids for the masked rows.

    Returns (order, gids_sorted, num_groups, first_in_group):
    - order: [n] permutation sorting rows by (valid desc, keys)
    - gids_sorted: [n] dense group id per *sorted* position; invalid rows
      get id ``num_groups`` (a trailing dummy segment)
    - num_groups: scalar count of real groups
    - first_in_group: [n] bool, True at the first sorted row of each group
    """
    n = valid.shape[0]
    sort_keys: List[jnp.ndarray] = [_as_sortable(k) for k in reversed(list(keys))]
    # primary key: invalid rows last  (lexsort: last key is primary)
    sort_keys.append(jnp.where(valid, 0, 1).astype(jnp.int32))
    order = jnp.lexsort(sort_keys)

    valid_s = valid[order]
    boundary = jnp.zeros((n,), dtype=jnp.bool_)
    for k in keys:
        ks = k[order]
        diff = jnp.concatenate([jnp.ones((1,), jnp.bool_), ks[1:] != ks[:-1]])
        boundary = boundary | diff
    if not list(keys):
        boundary = boundary.at[0].set(True)
    # only valid rows start groups; the first invalid row starts the dummy
    first_invalid = jnp.concatenate(
        [valid_s[:1] == False, valid_s[1:] != valid_s[:-1]]  # noqa: E712
    )
    boundary = (boundary & valid_s) | (first_invalid & ~valid_s)
    # make sure position 0 is a boundary (group 0 or dummy)
    boundary = boundary.at[0].set(True)

    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1  # dense ids in sorted order
    num_groups = jnp.sum((boundary & valid_s).astype(jnp.int32))
    first_in_group = boundary & valid_s
    return order, seg, num_groups, first_in_group


def segment_aggregate(
    values: jnp.ndarray,
    seg: jnp.ndarray,
    capacity: int,
    op: str,
    valid_s: jnp.ndarray,
) -> jnp.ndarray:
    """Aggregate sorted ``values`` per segment id into [capacity] output.

    op: "sum" | "min" | "max" | "count" | "any" | "all"
    Invalid rows must already carry the op's identity or sit in the dummy
    trailing segment (>= capacity is dropped by segment_* ops: we clamp
    ids of invalid rows to capacity).
    """
    num_segments = capacity + 1  # one extra dummy slot
    seg = jnp.where(valid_s, seg, capacity)
    if op == "count":
        out = jax.ops.segment_sum(
            jnp.ones_like(seg, dtype=jnp.int32), seg, num_segments=num_segments
        )
    elif op == "sum":
        out = jax.ops.segment_sum(values, seg, num_segments=num_segments)
    elif op == "min":
        out = jax.ops.segment_min(values, seg, num_segments=num_segments)
    elif op == "max":
        out = jax.ops.segment_max(values, seg, num_segments=num_segments)
    elif op == "any":
        out = jax.ops.segment_max(values.astype(jnp.int32), seg, num_segments=num_segments).astype(jnp.bool_)
    elif op == "all":
        out = jax.ops.segment_min(values.astype(jnp.int32), seg, num_segments=num_segments).astype(jnp.bool_)
    else:
        raise ValueError(f"unknown aggregate op {op!r}")
    return out[:capacity]


def distinct_mask(keys: Sequence[jnp.ndarray], valid: jnp.ndarray) -> jnp.ndarray:
    """Mask keeping one representative row per distinct key combination.

    Used for SELECT DISTINCT: rows stay in place (no reordering); the
    first occurrence in sort order survives.
    """
    order, _seg, _num, first = group_ids(keys, valid)
    n = valid.shape[0]
    keep = jnp.zeros((n,), dtype=jnp.bool_).at[order].set(first)
    return keep & valid
