"""Mask compaction: gather valid rows to the front with a static size."""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def compact_indices(
    valid: jnp.ndarray, out_capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Indices of valid rows packed to the front.

    Returns (idx[out_capacity], out_valid[out_capacity]); gather columns
    with ``col[idx]`` after masking by out_valid. Rows beyond
    out_capacity drop (callers size capacity >= plausible counts).
    """
    (idx,) = jnp.nonzero(valid, size=out_capacity, fill_value=-1)
    out_valid = idx >= 0
    return jnp.where(out_valid, idx, 0), out_valid
