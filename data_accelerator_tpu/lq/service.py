"""LiveQueryService: the multi-tenant interactive serving plane.

Composes the three serving-plane parts into the one object the REST
surface talks to:

- ``SessionManager`` — tenant registry, TTL reaping, quota admission
  (typed rejections the REST layer maps to 429 + ``Retry-After``);
- ``WarmKernelCache`` — signature-keyed resident kernels under a
  DX2xx-priced HBM budget, persistent-compile-cache re-warm;
- ``DispatchCoalescer`` — per-signature micro-batching with deadline
  ticks (``lq.maxbatchwaitms``).

Conf block (``datax.job.process.lq.*``, designer ``jobLq*`` knobs via
generation S400/S650):

==========================  =======  =====================================
key                         default  meaning
==========================  =======  =====================================
``maxbatchwaitms``          8        dispatch tick deadline per signature
``maxfanin``                64       calls that force a tick early
``sessionttlseconds``       1800     idle session TTL (both surfaces)
``maxsessions``             1024     service-wide session cap
``tenant.maxsessions``      8        per-tenant concurrent session quota
``tenant.maxqps``           50       per-tenant execute QPS quota
``hbmbudgetmb``             (model)  warm-kernel residency budget; the
                                     default is ``costmodel.warm_kernel_
                                     cache_budget_bytes()`` (25% of one
                                     fleet-spec chip)
``exectimeoutseconds``      30       caller wait bound per execute
``ticker``                  auto     background tick thread; when off,
                                     every execute flushes its own tick
                                     (the synchronous one-box mode)
==========================  =======  =====================================

Observability: ``LQ_*`` gauges/counters + the ``Latency-LQExec-pNN``
histogram series (exemplar-bearing, like every other latency family) —
all registered in ``constants.MetricName`` and documented in
OBSERVABILITY.md ("LiveQuery serving metrics"). ``LQ_Backlog`` is the
pilot-visible pressure signal, and the default ``lq-latency-slo`` alert
rule (obs/alerts.py) votes ``backpressure`` while p99 exec latency is
over SLO — one action vocabulary with the autopilot.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..obs.histogram import HistogramRegistry
from ..obs.metrics import MetricLogger
from .coalescer import DEFAULT_EXEC_TIMEOUT_S, DispatchCoalescer
from .session import AdmissionRejected, SessionManager
from .warmcache import WarmKernelCache

LQ_FLOW = "LiveQuery"
LQ_APP = "DATAX-LiveQuery"
#: histogram stage of one end-to-end execute (queue wait + dispatch) —
#: a member of ``constants.MetricName.STAGES`` so alert rules resolve
#: ``Latency-LQExec-pNN`` through the live histogram like any stage
LQ_EXEC_STAGE = "lq-exec"

_CONF_PREFIX = "datax.job.process.lq."


def _conf_get(conf, key: str, default):
    """Read ``datax.job.process.lq.<key>`` from a SettingDictionary, a
    flat conf dict, or a bare {key: value} dict."""
    if conf is None:
        return default
    getter = getattr(conf, "get", None)
    if getter is None:
        return default
    v = getter(_CONF_PREFIX + key)
    if v is None:
        v = getter(key)
    if v in (None, ""):
        return default
    if isinstance(default, bool):
        return str(v).lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(float(v))
    if isinstance(default, float):
        return float(v)
    return v


class LiveQueryService:
    """The serving plane facade: session lifecycle + coalesced execute
    + the LQ_* observability surface."""

    def __init__(
        self,
        conf=None,
        session_manager: Optional[SessionManager] = None,
        compile_conf: Optional[Dict[str, str]] = None,
        store=None,
        now_fn=time.time,
        ticker: Optional[bool] = None,
    ):
        self.max_wait_ms = _conf_get(conf, "maxbatchwaitms", 8.0)
        self.max_fanin = _conf_get(conf, "maxfanin", 64)
        self.exec_timeout_s = _conf_get(
            conf, "exectimeoutseconds", DEFAULT_EXEC_TIMEOUT_S
        )
        ttl_s = _conf_get(conf, "sessionttlseconds", 1800.0)
        budget_mb = _conf_get(conf, "hbmbudgetmb", 0)
        self.sessions = session_manager or SessionManager(
            ttl_s=ttl_s,
            max_sessions=_conf_get(conf, "maxsessions", 1024),
            tenant_max_sessions=_conf_get(conf, "tenant.maxsessions", 8),
            tenant_max_qps=_conf_get(conf, "tenant.maxqps", 50.0),
            now_fn=now_fn,
        )
        self.cache = WarmKernelCache(
            budget_bytes=int(budget_mb) * 1024 * 1024 if budget_mb else None,
            compile_conf=compile_conf,
            now_fn=now_fn,
        )
        self.coalescer = DispatchCoalescer(
            self.cache,
            max_wait_ms=self.max_wait_ms,
            max_fanin=self.max_fanin,
        )
        # a closed/reaped session's queued calls fail fast instead of
        # waiting out the exec timeout
        self.sessions.on_reap(
            lambda s: self.coalescer.cancel_session(s.id)
        )
        self.histograms = HistogramRegistry()
        self.metrics = MetricLogger(LQ_APP, store=store)
        # boot-time conf audit (runtime/confaudit.py): a full prefixed
        # conf handed to the service is replayed through the DX10xx
        # lattice validator — DX1006 flight records + Conf_* gauges for
        # unknown/out-of-bounds keys. Bare {key: value} dicts (the
        # test-convenience form) carry no datax.job.process.* keys and
        # audit as empty. Advisory: never blocks boot.
        from ..runtime.confaudit import from_conf as _confaudit_from_conf

        self.conf_audit = _confaudit_from_conf(
            conf, subject="lq", metric_logger=self.metrics
        )
        self._qps_window: List[float] = []  # completion stamps (10 s)
        self._qps_lock = threading.Lock()
        self._ticker: Optional[threading.Thread] = None
        self._ticker_stop = threading.Event()
        want_ticker = _conf_get(conf, "ticker", bool(ticker))
        if want_ticker:
            self.start_ticker()

    # -- ticker -----------------------------------------------------------
    @property
    def ticking(self) -> bool:
        return self._ticker is not None and self._ticker.is_alive()

    def start_ticker(self) -> None:
        """Run deadline ticks on a background thread — the serving
        mode: REST threads enqueue and block; this thread dispatches."""
        if self.ticking:
            return
        self._ticker_stop.clear()

        def loop():
            interval = max(0.001, self.max_wait_ms / 2000.0)
            while not self._ticker_stop.wait(interval):
                try:
                    self.coalescer.run_due()
                except Exception:  # noqa: BLE001 — tick must never die
                    pass

        self._ticker = threading.Thread(
            target=loop, name="lq-ticker", daemon=True
        )
        self._ticker.start()

    def stop_ticker(self) -> None:
        self._ticker_stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=2.0)
            self._ticker = None

    # -- session lifecycle ------------------------------------------------
    def create_session(
        self,
        tenant: str,
        flow_name: str,
        schema_json: str,
        normalization: str = "Raw.*",
        sample_rows: Optional[List[dict]] = None,
        udfs: Optional[dict] = None,
        refdata_conf: Optional[Dict[str, str]] = None,
        debug: object = None,
    ) -> dict:
        s = self.sessions.create(
            tenant=tenant or "default",
            flow_name=flow_name,
            schema_json=schema_json,
            normalization=normalization,
            sample_rows=sample_rows,
            udfs=udfs,
            refdata_conf=refdata_conf,
            debug=debug,
        )
        return s.to_dict()

    def close_session(self, session_id: str) -> bool:
        self.coalescer.cancel_session(session_id)
        return self.sessions.close(session_id)

    def close_flow(self, flow_name: str) -> int:
        n = self.sessions.close_where(flow_name=flow_name)
        self.cache.evict_flow(flow_name)
        return n

    def list_sessions(self, tenant: Optional[str] = None) -> List[dict]:
        return [s.to_dict() for s in self.sessions.list(tenant=tenant)]

    # -- execute ----------------------------------------------------------
    def execute(self, session_id: str, query: str,
                max_rows: int = 100) -> dict:
        """One tenant execute through the serving plane: quota
        admission (typed reject, NO dispatch), coalescer enqueue, tick
        (background when the ticker runs, inline flush otherwise),
        result. Latency lands in the ``lq-exec`` histogram with the
        session id as exemplar."""
        t0 = time.monotonic()
        session = self.sessions.get(session_id)
        # admission BEFORE the coalescer ever sees the call: a rejected
        # tenant consumes zero queue slots and zero device dispatches
        self.sessions.admit_execute(session)
        pending = self.coalescer.submit(session, query, max_rows=max_rows)
        if not self.ticking:
            self.coalescer.flush()
        try:
            result = pending.wait(self.exec_timeout_s)
        finally:
            ms = (time.monotonic() - t0) * 1000.0
            self.histograms.observe(
                LQ_FLOW, LQ_EXEC_STAGE, ms, trace_id=session_id
            )
        with self._qps_lock:
            now = time.monotonic()
            self._qps_window.append(now)
            cutoff = now - 10.0
            while self._qps_window and self._qps_window[0] < cutoff:
                self._qps_window.pop(0)
        return result

    # -- observability ----------------------------------------------------
    def qps(self) -> float:
        with self._qps_lock:
            if len(self._qps_window) < 2:
                return float(len(self._qps_window))
            span = self._qps_window[-1] - self._qps_window[0]
            return (
                len(self._qps_window) / span if span > 0
                else float(len(self._qps_window))
            )

    def lq_metrics(self) -> Dict[str, float]:
        """The LQ_* gauge/counter snapshot plus the exec-latency
        percentiles — every name resolves through
        ``constants.MetricName`` (tier-1 asserted)."""
        sess = self.sessions.stats()
        cache = self.cache.stats()
        co = self.coalescer.stats()
        m = {
            "LQ_Sessions": float(sess["sessions"]),
            "LQ_Tenants": float(sess["tenants"]),
            "LQ_Qps": round(self.qps(), 3),
            "LQ_Backlog": float(co["backlog"]),
            "LQ_CoalesceFanin": float(co["avgFanin"]),
            "LQ_Dispatch_Count": float(co["dispatches"]),
            "LQ_Coalesced_Count": float(co["coalesced"]),
            "LQ_KernelBytes": float(cache["residentBytes"]),
            "LQ_KernelEvict_Count": float(cache["evictions"]),
            "LQ_Admission_Rejected_Count": float(sess["rejectedTotal"]),
        }
        for q in (50, 95, 99):
            v = self.histograms.percentile(LQ_FLOW, LQ_EXEC_STAGE, q)
            if v is not None:
                m[f"Latency-LQExec-p{q}"] = v
        return m

    def export_metrics(self) -> Dict[str, float]:
        """Push the LQ_* snapshot to the metric store (the same
        store/exposition path every engine series rides)."""
        m = self.lq_metrics()
        self.metrics.send_batch_metrics(m)
        return m

    def snapshot(self) -> dict:
        """The ``GET lq/stats`` payload: metrics + component detail."""
        return {
            "metrics": self.export_metrics(),
            "sessions": self.sessions.stats(),
            "cache": self.cache.stats(),
            "coalescer": self.coalescer.stats(),
            "maxBatchWaitMs": self.max_wait_ms,
            "ticking": self.ticking,
        }

    def stop(self) -> None:
        self.stop_ticker()


__all__ = [
    "AdmissionRejected",
    "LiveQueryService",
    "LQ_EXEC_STAGE",
    "LQ_FLOW",
]
