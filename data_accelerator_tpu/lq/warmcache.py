"""Signature-keyed warm-kernel LRU for the LiveQuery serving plane.

The scaling insight the whole serving plane rests on: a compiled
interactive kernel is keyed by its COMPILE SIGNATURE — flow hash x
pow2 row bucket x query shape — not by the session that asked for it.
Thousands of tenants viewing the same designer flow share ONE resident
kernel; the pow2 bucket lattice (``serve/livequery._capacity_for``,
the same lattice DX6xx proves finite for the transfer helpers) keeps
the set of reachable signatures bounded no matter how many users
connect. The jit-cache surface is therefore a function of the lattice,
not of tenant count — the property the coalescer's tier-1 proof
asserts with 256 concurrent sessions.

Residency is budgeted in the cost model's currency: each entry is
priced with the DX2xx per-kernel HBM model
(``analysis/deviceplan.analyze_processor(...).totals()``) and the LRU
evicts (counted — ``LQ_KernelEvict_Count``) when the resident total
exceeds ``costmodel.warm_kernel_cache_budget_bytes`` worth of chip
HBM. Eviction is cheap to undo: every kernel's conf carries the PR 9
persistent-compile-cache keys, so a re-admitted signature deserializes
its compile (~12 ms) instead of re-tracing (~830 ms) — re-warms are
counted separately so the dashboards can tell thrash from cold."""

from __future__ import annotations

import hashlib
import json
import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

logger = logging.getLogger(__name__)

#: conservative per-entry estimate when the DX2xx model cannot price a
#: kernel (lowering unavailable for an exotic query) — large enough
#: that fallback-sized entries still get evicted under pressure
FALLBACK_KERNEL_BYTES = 64 * 1024 * 1024


@dataclass(frozen=True)
class CompileSignature:
    """flow-hash x pow2 row bucket x query shape — the unit of compile
    sharing. Everything that can change a trace is in the flow hash
    (schema, normalization, refdata, udf set, debug flags, compile
    conf); everything that cannot is deliberately left out so sessions
    coalesce."""

    flow_hash: str
    row_bucket: int
    query_shape: str

    @property
    def key(self) -> str:
        return f"{self.flow_hash}:{self.row_bucket}:{self.query_shape}"


def _normalize_query(query: str) -> str:
    """Whitespace-insensitive query shape: the designer re-sending the
    same query with different formatting must not fork the compile
    surface."""
    return " ".join((query or "").split())


def flow_hash_for(
    flow_name: str,
    schema_json: str,
    normalization: str,
    refdata_conf: Optional[Dict[str, str]] = None,
    udfs: Optional[dict] = None,
    debug: object = None,
    compile_conf: Optional[Dict[str, str]] = None,
) -> str:
    """Digest of every session field that shapes the compiled trace."""
    h = hashlib.sha1()
    h.update(json.dumps([
        flow_name,
        schema_json,
        normalization,
        sorted((refdata_conf or {}).items()),
        sorted(udfs.keys()) if isinstance(udfs, dict) else bool(udfs),
        debug if isinstance(debug, (bool, type(None))) else sorted(
            dict(debug or {}).items()
        ),
        sorted((compile_conf or {}).items()),
    ], default=str).encode())
    return h.hexdigest()[:16]


def signature_for(session, query: str,
                  compile_conf: Optional[Dict[str, str]] = None
                  ) -> CompileSignature:
    """The compile signature of one execute: session flow fields +
    the pow2 bucket its row count pads into + the normalized query."""
    from ..serve.livequery import _capacity_for

    return CompileSignature(
        flow_hash=flow_hash_for(
            session.flow_name, session.schema_json, session.normalization,
            session.refdata_conf, session.udfs, session.debug,
            compile_conf,
        ),
        row_bucket=_capacity_for(len(session.sample_rows)),
        query_shape=_normalize_query(query),
    )


def rows_digest(rows) -> str:
    """Identity of one execute's input rows — the coalescer fans one
    dispatch out to every queued call whose (signature, rows digest,
    query, max_rows) match, which is the common many-users-one-
    dashboard case."""
    h = hashlib.sha1()
    for r in rows:
        h.update(json.dumps(r, sort_keys=True, default=str).encode())
        h.update(b"\n")
    return h.hexdigest()[:16]


class WarmKernel:
    """One resident compiled kernel: a ``serve.livequery.Kernel`` bound
    to a signature's flow fields and row bucket, executed with whatever
    rows the tick hands it (sessions in the same bucket share it)."""

    def __init__(self, signature: CompileSignature, kernel):
        self.signature = signature
        self.kernel = kernel
        self.hbm_bytes = 0
        self.sized_by = "unsized"
        self.last_used = 0.0
        self.executes = 0

    def execute(self, rows, query: str, max_rows: int) -> dict:
        # the tick runner is single-threaded per cache (the coalescer's
        # run lock), so re-pointing the kernel at this call's rows is
        # safe; capacity stays the signature's bucket by construction
        self.kernel.sample_rows = list(rows)
        self.executes += 1
        return self.kernel.execute(query, max_rows=max_rows)

    def step_cache_size(self) -> int:
        """Total jitted-step cache entries across this kernel's query
        processors — the number the coalescing proof asserts flat."""
        total = 0
        for proc in self.kernel._processors.values():
            n = proc._step_cache_size()
            total += int(n) if n is not None else 1
        return total


class WarmKernelCache:
    """LRU over ``WarmKernel`` entries, budgeted in modeled HBM bytes.

    ``budget_bytes`` defaults to ``costmodel.warm_kernel_cache_budget_
    bytes()`` (a headroom fraction of one fleet-spec chip). Entries are
    priced after their first execute compiles the processor; eviction
    never removes the entry the current tick is using."""

    def __init__(
        self,
        budget_bytes: Optional[int] = None,
        compile_conf: Optional[Dict[str, str]] = None,
        now_fn: Callable[[], float] = time.time,
    ):
        if budget_bytes is None:
            from ..analysis.costmodel import warm_kernel_cache_budget_bytes

            budget_bytes = warm_kernel_cache_budget_bytes()
        self.budget_bytes = int(budget_bytes)
        self.compile_conf = dict(compile_conf or {})
        self.now = now_fn
        self._entries: Dict[str, WarmKernel] = {}
        self._lock = threading.RLock()
        self._seen_signatures: set = set()
        self.evictions = 0
        self.rewarms = 0
        self.compiles = 0

    # -- acquisition ------------------------------------------------------
    def acquire(self, signature: CompileSignature, session) -> WarmKernel:
        """The signature's resident kernel, building one from the
        session's flow fields on miss. A miss for a signature seen
        before is a RE-WARM: the rebuild goes through the persistent
        compile cache (``compile_conf``), so it deserializes instead of
        re-tracing."""
        from ..serve.livequery import Kernel

        with self._lock:
            entry = self._entries.get(signature.key)
            if entry is None:
                if signature.key in self._seen_signatures:
                    self.rewarms += 1
                self._seen_signatures.add(signature.key)
                self.compiles += 1
                kernel = Kernel(
                    id=f"warm-{signature.flow_hash}-{signature.row_bucket}",
                    flow_name=session.flow_name,
                    schema_json=session.schema_json,
                    normalization=session.normalization,
                    sample_rows=list(session.sample_rows),
                    udfs=session.udfs,
                    refdata_conf=dict(session.refdata_conf or {}),
                    debug=session.debug,
                    compile_conf=dict(self.compile_conf),
                )
                entry = WarmKernel(signature, kernel)
                self._entries[signature.key] = entry
            entry.last_used = self.now()
            return entry

    # -- budget enforcement ----------------------------------------------
    def _price_entry(self, entry: WarmKernel) -> None:
        """Price the entry with the DX2xx per-kernel byte model over
        its compiled processors (the same totals the fleet packer
        consumes); fall back to a flat conservative estimate when the
        model can't lower the query."""
        try:
            from ..analysis.deviceplan import analyze_processor

            total = 0
            for proc in entry.kernel._processors.values():
                total += int(analyze_processor(proc).totals()["hbmBytes"])
            if total > 0:
                entry.hbm_bytes = total
                entry.sized_by = "model"
                return
        except Exception as e:  # noqa: BLE001 — sizing must not fail a query
            logger.debug("kernel HBM model failed for %s: %s",
                         entry.signature.key, e)
        entry.hbm_bytes = FALLBACK_KERNEL_BYTES
        entry.sized_by = "fallback"

    def settle(self, in_use: Optional[WarmKernel] = None) -> int:
        """Price unsized entries and evict LRU until the resident total
        fits the budget (never evicting ``in_use``). Returns evictions
        this pass; the cumulative count feeds ``LQ_KernelEvict_Count``."""
        evicted = 0
        with self._lock:
            for entry in self._entries.values():
                if entry.sized_by == "unsized" and entry.kernel._processors:
                    self._price_entry(entry)
            while len(self._entries) > 1 \
                    and self.resident_bytes() > self.budget_bytes:
                victims = [
                    e for e in self._entries.values() if e is not in_use
                ]
                if not victims:
                    break
                lru = min(victims, key=lambda e: e.last_used)
                del self._entries[lru.signature.key]
                self.evictions += 1
                evicted += 1
        return evicted

    def evict_flow(self, flow_name: str) -> int:
        """Drop every resident kernel built for ``flow_name`` (flow
        delete / refresh cascade)."""
        with self._lock:
            doomed = [
                k for k, e in self._entries.items()
                if e.kernel.flow_name == flow_name
            ]
            for k in doomed:
                del self._entries[k]
            return len(doomed)

    # -- observability ----------------------------------------------------
    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.hbm_bytes for e in self._entries.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def step_cache_entries(self) -> int:
        """Total jitted-step entries across resident kernels — the
        coalescing proof's bounded quantity."""
        with self._lock:
            return sum(
                e.step_cache_size() for e in self._entries.values()
            )

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "residentBytes": self.resident_bytes(),
                "budgetBytes": self.budget_bytes,
                "evictions": self.evictions,
                "rewarms": self.rewarms,
                "compiles": self.compiles,
                "stepCacheEntries": self.step_cache_entries(),
            }
