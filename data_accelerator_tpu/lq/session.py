"""Multi-tenant session registry for the LiveQuery serving plane.

reference: the reference platform's InteractiveQueryService tracks one
kernel list per cluster with a recycle timer (KernelService.cs:135-190)
and relies on the designer to be the only tenant; a serving plane that
multiplexes "as many users as you can imagine" (ROADMAP item 3) needs
the registry to be the admission point instead: per-tenant session and
QPS quotas enforced BEFORE any device work is queued, typed rejections
the REST surface can turn into 429 + Retry-After, and TTL/idle reaping
on every access path so abandoned sessions can never pin kernels.

One registry serves BOTH surfaces: the new ``lq/`` session service and
the legacy ``serve/livequery.py`` ``KernelService`` (whose REST-created
kernels previously leaked — GC only ran inside ``create_kernel``, so a
designer that stopped creating kernels kept every old one alive
forever). The legacy surface registers its kernels under the
``LEGACY_TENANT`` with the evict-oldest-on-full policy it always had;
the serving plane registers real tenants with the reject-with-429
policy a multi-tenant admission gate needs. Quota state is per tenant,
session records are one flat dict — ``delete per flow`` and ``reap``
see both surfaces.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

DEFAULT_SESSION_TTL_S = 30 * 60
DEFAULT_MAX_SESSIONS = 1024
DEFAULT_TENANT_MAX_SESSIONS = 8
DEFAULT_TENANT_MAX_QPS = 50.0

#: the tenant the legacy ``KernelService`` registers kernels under —
#: exempt from per-tenant quotas (the designer was never quota'd) but
#: fully subject to TTL reaping and its own capacity policy.
LEGACY_TENANT = "__legacy__"

#: typed rejection kinds — the contract between admission, the
#: ``LQ_Admission_Rejected_Count`` counter and the REST 429 body.
REJECT_TENANT_SESSIONS = "tenant-sessions"
REJECT_SERVICE_SESSIONS = "service-sessions"
REJECT_TENANT_QPS = "tenant-qps"
REJECT_KINDS = (
    REJECT_TENANT_SESSIONS, REJECT_SERVICE_SESSIONS, REJECT_TENANT_QPS,
)


class AdmissionRejected(Exception):
    """A session/execute was refused at admission — BEFORE any kernel
    compile or device dispatch was queued. ``kind`` is one of
    ``REJECT_KINDS``; ``retry_after_s`` is the hint the REST surface
    sends as ``Retry-After``."""

    def __init__(self, kind: str, message: str, tenant: str = "",
                 retry_after_s: float = 1.0):
        super().__init__(message)
        self.kind = kind
        self.tenant = tenant
        self.retry_after_s = float(retry_after_s)

    def to_dict(self) -> dict:
        return {
            "message": str(self),
            "kind": self.kind,
            "tenant": self.tenant,
            "retryAfterSeconds": round(self.retry_after_s, 3),
        }


class QuotaBucket:
    """Strict per-tenant QPS token bucket.

    Unlike the pilot's source-backpressure ``TokenBucket`` (which
    always grants >= 1 so a throttled flow can observe its own drain),
    a quota bucket must be able to say NO: an over-quota tenant's
    execute is rejected outright and told when to come back."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 now_fn: Callable[[], float] = time.monotonic):
        self.rate = max(float(rate), 0.001)
        self.burst = float(burst) if burst is not None else max(
            1.0, self.rate
        )
        self.now = now_fn
        self._tokens = self.burst
        self._last = self.now()

    def _refill(self) -> None:
        now = self.now()
        self._tokens = min(
            self.burst, self._tokens + max(0.0, now - self._last) * self.rate
        )
        self._last = now

    def try_take(self, n: float = 1.0) -> bool:
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def retry_after_s(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will be available."""
        self._refill()
        missing = max(0.0, n - self._tokens)
        return missing / self.rate


@dataclass
class Session:
    """One tenant's interactive session: the flow-scoped inputs a
    kernel needs, but NO compiled state — compiled kernels live in the
    signature-keyed ``WarmKernelCache`` so the compile surface is
    bounded by the bucket lattice, not by session count."""

    id: str
    tenant: str
    flow_name: str
    schema_json: str = ""
    normalization: str = "Raw.*"
    sample_rows: List[dict] = field(default_factory=list)
    udfs: Optional[dict] = None
    refdata_conf: Dict[str, str] = field(default_factory=dict)
    debug: object = None
    created_at: float = 0.0
    last_used: float = 0.0
    executes: int = 0
    #: legacy surface parks its compiled Kernel object here
    payload: object = None

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "tenant": self.tenant,
            "flow": self.flow_name,
            "createdAt": self.created_at,
            "lastUsed": self.last_used,
            "executes": self.executes,
            "sampleRows": len(self.sample_rows),
        }


class _TenantState:
    def __init__(self, bucket: Optional[QuotaBucket]):
        self.bucket = bucket
        self.sessions = 0


class SessionManager:
    """Per-tenant session registry with TTL/idle reaping and quota
    admission. Thread-safe; every mutation reaps expired sessions
    first, so TTL eviction happens on EVERY access path (create,
    get, execute-admit, list) — the legacy leak is structurally gone."""

    def __init__(
        self,
        ttl_s: float = DEFAULT_SESSION_TTL_S,
        max_sessions: int = DEFAULT_MAX_SESSIONS,
        tenant_max_sessions: int = DEFAULT_TENANT_MAX_SESSIONS,
        tenant_max_qps: float = DEFAULT_TENANT_MAX_QPS,
        now_fn: Callable[[], float] = time.time,
    ):
        self.ttl_s = float(ttl_s)
        self.max_sessions = int(max_sessions)
        self.tenant_max_sessions = int(tenant_max_sessions)
        self.tenant_max_qps = float(tenant_max_qps)
        self.now = now_fn
        self._sessions: Dict[str, Session] = {}
        self._tenants: Dict[str, _TenantState] = {}
        self._lock = threading.RLock()
        self._reaped_total = 0
        self._rejected: Dict[str, int] = {k: 0 for k in REJECT_KINDS}
        # reap hooks: the serving plane subscribes so a reaped session's
        # queued work can be failed instead of orphaned
        self._on_reap: List[Callable[[Session], None]] = []

    # -- internals --------------------------------------------------------
    def _tenant(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            bucket = (
                None if tenant == LEGACY_TENANT
                else QuotaBucket(self.tenant_max_qps)
            )
            st = self._tenants[tenant] = _TenantState(bucket)
        return st

    def _drop_locked(self, sid: str, reaped: bool = False) -> Optional[Session]:
        s = self._sessions.pop(sid, None)
        if s is None:
            return None
        st = self._tenants.get(s.tenant)
        if st is not None:
            st.sessions = max(0, st.sessions - 1)
            if st.sessions == 0 and s.tenant != LEGACY_TENANT:
                # forget idle tenants so quota state can't grow forever
                del self._tenants[s.tenant]
        if reaped:
            self._reaped_total += 1
        return s

    def _reap_locked(self) -> List[Session]:
        now = self.now()
        doomed = [
            sid for sid, s in self._sessions.items()
            if now - s.last_used > self.ttl_s
        ]
        return [self._drop_locked(sid, reaped=True) for sid in doomed]

    def _notify_reaped(self, reaped: List[Session]) -> None:
        for s in reaped:
            for hook in self._on_reap:
                try:
                    hook(s)
                except Exception:  # noqa: BLE001 — hooks must not gate GC
                    pass

    def on_reap(self, hook: Callable[[Session], None]) -> None:
        self._on_reap.append(hook)

    def _reject(self, kind: str, message: str, tenant: str,
                retry_after_s: float) -> AdmissionRejected:
        self._rejected[kind] = self._rejected.get(kind, 0) + 1
        return AdmissionRejected(
            kind, message, tenant=tenant, retry_after_s=retry_after_s
        )

    # -- lifecycle --------------------------------------------------------
    def create(
        self,
        tenant: str,
        flow_name: str,
        schema_json: str = "",
        normalization: str = "Raw.*",
        sample_rows: Optional[List[dict]] = None,
        udfs: Optional[dict] = None,
        refdata_conf: Optional[Dict[str, str]] = None,
        debug: object = None,
        payload: object = None,
        evict_on_full: bool = False,
        cap: Optional[int] = None,
    ) -> Session:
        """Admit + register a session. ``evict_on_full``/``cap`` are the
        legacy surface's policy (evict the oldest-idle kernel instead of
        rejecting, against its own ``max_kernels`` cap); the serving
        plane leaves them unset and gets typed 429-able rejections."""
        with self._lock:
            reaped = self._reap_locked()
            st = self._tenant(tenant)
            service_cap = int(cap) if cap is not None else self.max_sessions
            pool = (
                st.sessions if cap is not None
                else len(self._sessions)
            )
            if pool >= service_cap:
                if evict_on_full:
                    candidates = [
                        s for s in self._sessions.values()
                        if cap is None or s.tenant == tenant
                    ]
                    while pool >= service_cap and candidates:
                        oldest = min(candidates, key=lambda s: s.last_used)
                        candidates.remove(oldest)
                        self._drop_locked(oldest.id)
                        pool -= 1
                else:
                    raise self._reject(
                        REJECT_SERVICE_SESSIONS,
                        f"service session capacity {service_cap} reached",
                        tenant, retry_after_s=min(self.ttl_s, 30.0),
                    )
            if tenant != LEGACY_TENANT \
                    and st.sessions >= self.tenant_max_sessions:
                raise self._reject(
                    REJECT_TENANT_SESSIONS,
                    f"tenant '{tenant}' session quota "
                    f"{self.tenant_max_sessions} reached",
                    tenant, retry_after_s=min(self.ttl_s, 30.0),
                )
            now = self.now()
            s = Session(
                id=uuid.uuid4().hex[:12],
                tenant=tenant,
                flow_name=flow_name,
                schema_json=schema_json,
                normalization=normalization,
                sample_rows=list(sample_rows or []),
                udfs=udfs,
                refdata_conf=dict(refdata_conf or {}),
                debug=debug,
                created_at=now,
                last_used=now,
                payload=payload,
            )
            self._sessions[s.id] = s
            st.sessions += 1
        self._notify_reaped(reaped)
        return s

    def get(self, session_id: str, touch: bool = True) -> Session:
        with self._lock:
            reaped = self._reap_locked()
            s = self._sessions.get(session_id)
            if s is not None and touch:
                s.last_used = self.now()
        self._notify_reaped(reaped)
        if s is None:
            raise KeyError(
                f"session '{session_id}' not found (expired or closed?)"
            )
        return s

    def admit_execute(self, session: Session) -> None:
        """Per-tenant QPS admission for one execute; raises the typed
        rejection BEFORE the call reaches the coalescer (a quota'd
        tenant never consumes a device dispatch)."""
        with self._lock:
            st = self._tenant(session.tenant)
            if st.bucket is not None and not st.bucket.try_take(1.0):
                raise self._reject(
                    REJECT_TENANT_QPS,
                    f"tenant '{session.tenant}' over "
                    f"{self.tenant_max_qps:g} qps quota",
                    session.tenant,
                    retry_after_s=max(0.02, st.bucket.retry_after_s(1.0)),
                )
            session.last_used = self.now()
            session.executes += 1

    def close(self, session_id: str) -> bool:
        with self._lock:
            return self._drop_locked(session_id) is not None

    def close_where(self, flow_name: Optional[str] = None,
                    tenant: Optional[str] = None) -> int:
        with self._lock:
            doomed = [
                sid for sid, s in self._sessions.items()
                if (flow_name is None or s.flow_name == flow_name)
                and (tenant is None or s.tenant == tenant)
            ]
            for sid in doomed:
                self._drop_locked(sid)
            return len(doomed)

    def reap(self) -> int:
        with self._lock:
            reaped = self._reap_locked()
        self._notify_reaped(reaped)
        return len(reaped)

    def list(self, tenant: Optional[str] = None,
             exclude_tenant: Optional[str] = None) -> List[Session]:
        with self._lock:
            reaped = self._reap_locked()
            out = [
                s for s in self._sessions.values()
                if (tenant is None or s.tenant == tenant)
                and (exclude_tenant is None or s.tenant != exclude_tenant)
            ]
        self._notify_reaped(reaped)
        return out

    # -- observability ----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            tenants = {
                t for t, st in self._tenants.items()
                if st.sessions > 0 and t != LEGACY_TENANT
            }
            return {
                "sessions": len(self._sessions),
                "tenants": len(tenants),
                "reaped": self._reaped_total,
                "rejected": dict(self._rejected),
                "rejectedTotal": sum(self._rejected.values()),
            }
