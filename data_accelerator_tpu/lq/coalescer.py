"""Micro-batched device dispatch for concurrent LiveQuery sessions.

The serving plane's throughput lever: incoming ``execute()`` calls
queue PER COMPILE SIGNATURE (flow-hash x pow2 row bucket x query
shape — ``warmcache.signature_for``), and a scheduling tick fires each
signature's queue as ONE dispatch group against that signature's single
resident kernel. Calls whose payload is identical (same rows digest,
query, max_rows — the many-users-one-dashboard case) share literally
one device dispatch and one result object; calls with distinct rows in
the same signature share the COMPILED entry (their rows pad into the
same pow2 bucket, so the trace is reused — no recompile, the jit-cache
surface stays bounded by the lattice while QPS scales with tenants).

Ticks are deadline-based: a queue fires when its oldest call has
waited ``max_wait_ms`` (conf ``datax.job.process.lq.maxbatchwaitms``)
or when it reaches ``max_fanin`` calls — so a loaded service amortizes
dispatches across tenants, and an idle one still answers a lone
interactive user within one deadline. A kernel failure mid-tick fails
ONLY the calls of the payload that raised; other payloads in the group
still resolve, and the next tick retries fresh (the compiled entry is
dropped so a poisoned trace cannot wedge the signature).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .warmcache import (
    CompileSignature,
    WarmKernelCache,
    rows_digest,
    signature_for,
)

DEFAULT_MAX_WAIT_MS = 8.0
DEFAULT_MAX_FANIN = 64
DEFAULT_EXEC_TIMEOUT_S = 30.0


class ExecCancelled(RuntimeError):
    """The queued call's session went away before its tick fired."""


class PendingExec:
    """One queued execute: callers block on ``wait``; the tick runner
    resolves or fails it."""

    def __init__(self, session_id: str, tenant: str, query: str,
                 max_rows: int, rows: List[dict], enqueued_at: float):
        self.session_id = session_id
        self.tenant = tenant
        self.query = query
        self.max_rows = int(max_rows)
        self.rows = rows
        self.rows_key = rows_digest(rows)
        self.enqueued_at = enqueued_at
        self._event = threading.Event()
        self._result: Optional[dict] = None
        self._exc: Optional[BaseException] = None

    @property
    def payload_key(self) -> Tuple[str, str, int]:
        return (self.rows_key, self.query, self.max_rows)

    def resolve(self, result: dict) -> None:
        self._result = result
        self._event.set()

    def fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout_s: float = DEFAULT_EXEC_TIMEOUT_S) -> dict:
        if not self._event.wait(timeout_s):
            raise TimeoutError(
                f"LiveQuery execute timed out after {timeout_s:g}s "
                "(dispatch tick never fired?)"
            )
        if self._exc is not None:
            raise self._exc
        return self._result  # type: ignore[return-value]


class DispatchCoalescer:
    """Per-signature queues + the deadline tick that drains them."""

    def __init__(
        self,
        cache: WarmKernelCache,
        max_wait_ms: float = DEFAULT_MAX_WAIT_MS,
        max_fanin: int = DEFAULT_MAX_FANIN,
        now_fn: Callable[[], float] = time.monotonic,
    ):
        self.cache = cache
        self.max_wait_ms = float(max_wait_ms)
        self.max_fanin = int(max_fanin)
        self.now = now_fn
        self._queues: Dict[str, Tuple[CompileSignature, List[PendingExec]]] = {}
        self._sessions_of_queue: Dict[str, object] = {}
        self._lock = threading.Lock()
        # serializes tick execution: one group runs at a time, so the
        # shared kernels' row re-pointing is single-threaded
        self._run_lock = threading.Lock()
        # cumulative counters (service exports them as LQ_* series)
        self.ticks = 0
        self.calls = 0
        self.dispatches = 0
        self.failed_dispatches = 0
        self.last_fanin = 0
        self.max_fanin_seen = 0

    # -- intake -----------------------------------------------------------
    def submit(self, session, query: str, max_rows: int = 100) -> PendingExec:
        """Queue one execute under its compile signature; returns the
        pending handle the caller blocks on. Quota admission happens
        BEFORE this (``SessionManager.admit_execute``) — a rejected
        call never reaches a queue, so it can never consume a
        dispatch."""
        sig = signature_for(session, query, self.cache.compile_conf)
        call = PendingExec(
            session.id, session.tenant, query, max_rows,
            list(session.sample_rows), self.now(),
        )
        with self._lock:
            entry = self._queues.get(sig.key)
            if entry is None:
                entry = self._queues[sig.key] = (sig, [])
                # the first queued session is the template the cache
                # builds the signature's kernel from on miss
                self._sessions_of_queue[sig.key] = session
            entry[1].append(call)
            self.calls += 1
        return call

    def cancel_session(self, session_id: str) -> int:
        """Fail every queued call of a reaped/closed session (its tick
        has not fired yet, so no device work is lost)."""
        cancelled = 0
        with self._lock:
            for sig_key in list(self._queues):
                sig, calls = self._queues[sig_key]
                keep = []
                for c in calls:
                    if c.session_id == session_id:
                        c.fail(ExecCancelled(
                            f"session '{session_id}' closed before its "
                            "dispatch tick fired"
                        ))
                        cancelled += 1
                    else:
                        keep.append(c)
                if keep:
                    self._queues[sig_key] = (sig, keep)
                else:
                    del self._queues[sig_key]
                    self._sessions_of_queue.pop(sig_key, None)
        return cancelled

    # -- scheduling -------------------------------------------------------
    def backlog(self) -> int:
        """Queued, not-yet-dispatched calls — the pilot-visible
        pressure signal (``LQ_Backlog``)."""
        with self._lock:
            return sum(len(calls) for _, calls in self._queues.values())

    def _due_locked(self, now: float, force: bool) -> List[str]:
        due = []
        for sig_key, (_, calls) in self._queues.items():
            if not calls:
                continue
            age_ms = (now - calls[0].enqueued_at) * 1000.0
            if force or age_ms >= self.max_wait_ms \
                    or len(calls) >= self.max_fanin:
                due.append(sig_key)
        return due

    def run_due(self, now: Optional[float] = None, force: bool = False) -> int:
        """Run one scheduling tick: every signature queue past its
        deadline (all of them when ``force``) fires as one dispatch
        group. Returns the number of groups run."""
        now = self.now() if now is None else now
        with self._lock:
            due = self._due_locked(now, force)
            groups = []
            for sig_key in due:
                sig, calls = self._queues.pop(sig_key)
                template = self._sessions_of_queue.pop(sig_key)
                groups.append((sig, template, calls))
        for sig, template, calls in groups:
            self._run_group(sig, template, calls)
        return len(groups)

    def flush(self) -> int:
        """Fire every queue now — the no-ticker (synchronous) mode and
        the test harness's determinism hook."""
        return self.run_due(force=True)

    # -- execution --------------------------------------------------------
    def _run_group(self, sig: CompileSignature, template,
                   calls: List[PendingExec]) -> None:
        with self._run_lock:
            self.ticks += 1
            self.last_fanin = len(calls)
            self.max_fanin_seen = max(self.max_fanin_seen, len(calls))
            try:
                entry = self.cache.acquire(sig, template)
            except Exception as e:  # noqa: BLE001 — building the kernel failed
                for c in calls:
                    c.fail(e)
                self.failed_dispatches += 1
                return
            # one dispatch per DISTINCT payload; identical payloads
            # (the dominant shared-dashboard case) share one result
            by_payload: Dict[Tuple[str, str, int], List[PendingExec]] = {}
            for c in calls:
                by_payload.setdefault(c.payload_key, []).append(c)
            poisoned = False
            for payload_calls in by_payload.values():
                first = payload_calls[0]
                try:
                    result = entry.execute(
                        first.rows, first.query, first.max_rows
                    )
                    self.dispatches += 1
                except Exception as e:  # noqa: BLE001 — per-payload isolation
                    self.failed_dispatches += 1
                    poisoned = True
                    for c in payload_calls:
                        c.fail(e)
                    continue
                for c in payload_calls:
                    c.resolve(result)
            if poisoned:
                # a trace that raised mid-tick cannot be trusted to
                # serve the next tick — drop the entry; the next
                # acquire re-warms through the persistent compile cache
                with self.cache._lock:
                    self.cache._entries.pop(sig.key, None)
            self.cache.settle(in_use=None if poisoned else entry)

    # -- observability ----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            backlog = sum(len(calls) for _, calls in self._queues.values())
        return {
            "ticks": self.ticks,
            "calls": self.calls,
            "dispatches": self.dispatches,
            "failedDispatches": self.failed_dispatches,
            "coalesced": max(0, self.calls - self.dispatches),
            "backlog": backlog,
            "lastFanin": self.last_fanin,
            "maxFaninSeen": self.max_fanin_seen,
            "avgFanin": round(self.calls / self.ticks, 3) if self.ticks else 0.0,
        }
