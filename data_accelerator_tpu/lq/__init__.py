"""LiveQuery serving plane: multi-tenant sessions, micro-batched
device dispatch, warm-kernel residency.

The reference platform's signature experience is interactive LiveQuery
from the designer (SURVEY §1, the L5/L3 zero-code tier); this package
scales that experience to the ROADMAP's "millions of users" axis by
multiplexing thousands of concurrent tenant sessions onto a few chips:

- ``session``   — per-tenant registry, TTL reaping, quota admission
                  (typed rejections → REST 429 + Retry-After);
- ``warmcache`` — compile-signature-keyed resident kernels (flow-hash x
                  pow2 row bucket x query shape) under a DX2xx-priced
                  HBM budget with persistent-compile-cache re-warm;
- ``coalescer`` — per-signature micro-batching: one dispatch group per
                  signature per deadline tick, identical payloads share
                  one device dispatch, the jit-cache surface stays
                  bounded by the bucket lattice while QPS scales;
- ``service``   — the facade the REST surface (serve/restapi.py
                  ``lq/*`` routes) talks to, with the ``LQ_*`` /
                  ``Latency-LQExec-pNN`` observability surface.

Imports are lazy (PEP 562): ``serve/livequery.py`` imports the session
registry from here while ``warmcache`` imports the ``Kernel`` machinery
from there — laziness keeps the cycle inert.
"""

_EXPORTS = {
    "AdmissionRejected": ".session",
    "Session": ".session",
    "SessionManager": ".session",
    "LEGACY_TENANT": ".session",
    "CompileSignature": ".warmcache",
    "WarmKernelCache": ".warmcache",
    "signature_for": ".warmcache",
    "DispatchCoalescer": ".coalescer",
    "PendingExec": ".coalescer",
    "LiveQueryService": ".service",
    "LQ_EXEC_STAGE": ".service",
    "LQ_FLOW": ".service",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod, __name__), name)
