"""Canned e2e scenario suites over the live REST API.

reference: Tests/DataXScenarios/{SaveAndDeploy,
InteractiveQueryAndSchemaGenScenarios}.cs — [Step]-attributed HTTP
sequences sharing a ScenarioContext, run by ScenarioTester against a
deployed instance and scheduled continuously by Services/JobRunner as
the production liveness probe.

Each builder returns a Scenario whose steps hit the given base URL
(website or gateway; pass a bearer token for the gateway). Wire into
JobRunner for the scheduled-probe role:

    runner = JobRunner([save_and_deploy(url), schema_and_query(url)])
    runner.start()
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Optional

from .scenario import Scenario, ScenarioContext

_SCHEMA = json.dumps({"type": "struct", "fields": [
    {"name": "deviceId", "type": "long", "nullable": False,
     "metadata": {"allowedValues": [1, 2, 3]}},
    {"name": "temperature", "type": "double", "nullable": False,
     "metadata": {"minValue": 0, "maxValue": 100}},
]})


def probe_deploy_gui(flow_name: str = "probe-deploy") -> dict:
    """The SaveAndDeploy probe's flow config — module-level so the
    analyzer self-lint (tests/test_analysis.py) can assert the shipped
    probe stays diagnostics-clean."""
    return {
        "name": flow_name,
        "displayName": "Probe Deploy",
        "input": {"mode": "streaming", "type": "local", "properties": {
            "inputSchemaFile": _SCHEMA,
            "normalizationSnippet": "Raw.*",
        }},
        "process": {"queries": [
            "--DataXQuery--\n"
            "Hot = SELECT deviceId, temperature FROM DataXProcessedInput "
            "WHERE temperature > 50;\n"
            "OUTPUT Hot TO HotConsole;"
        ]},
        "outputs": [{"id": "HotConsole", "type": "console",
                     "properties": {}}],
    }


def shipped_flow_guis() -> list:
    """Every flow config this module ships — the analyzer self-lint
    surface (all must produce zero error diagnostics)."""
    return [probe_deploy_gui()]


def _call(ctx: ScenarioContext, method: str, path: str, body=None):
    url = f"{ctx['base_url'].rstrip('/')}{path}"
    headers = {"Content-Type": "application/json"}
    if ctx.get("token"):
        headers["Authorization"] = f"Bearer {ctx['token']}"
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode() if body is not None else None,
        headers=headers,
        method=method,
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        payload = json.loads(r.read() or b"{}")
    return payload.get("result", payload)


def save_and_deploy(
    base_url: str,
    flow_name: str = "probe-deploy",
    token: Optional[str] = None,
    batches: int = 2,
) -> Scenario:
    """Save flow -> generate configs -> start -> jobs running -> stop ->
    delete (reference: SaveAndDeploy.cs over FlowManagementController)."""
    sc = Scenario(f"SaveAndDeploy")

    @sc.step
    def init_context(ctx):
        ctx.setdefault("base_url", base_url)
        ctx.setdefault("token", token)

    @sc.step
    def save_flow(ctx):
        r = _call(ctx, "POST", "/api/flow/flow/save",
                  probe_deploy_gui(flow_name))
        assert r.get("name") == flow_name, r

    @sc.step
    def generate_configs(ctx):
        r = _call(ctx, "POST", "/api/flow/flow/generateconfigs",
                  {"flowName": flow_name})
        assert r.get("jobNames"), r
        ctx["jobNames"] = r["jobNames"]

    @sc.step
    def start_jobs(ctx):
        r = _call(ctx, "POST", "/api/flow/flow/startjobs",
                  {"flowName": flow_name, "batches": batches})
        assert len(r) == len(ctx["jobNames"]), r

    @sc.step
    def jobs_reach_terminal_state(ctx):
        deadline = time.time() + 60
        states = {}
        while time.time() < deadline:
            jobs = _call(ctx, "POST", "/api/flow/job/getbynames",
                         {"jobNames": ctx["jobNames"]})
            states = {j["name"]: j.get("state") for j in jobs if j}
            if states and all(s in ("idle", "success")
                              for s in states.values()):
                return  # finite-batch run completed
            if any(s == "error" for s in states.values()):
                raise AssertionError(f"job failed: {states}")
            _call(ctx, "POST", "/api/flow/job/syncall", {})
            time.sleep(1)
        raise AssertionError(f"jobs never settled: {states}")

    @sc.step
    def stop_and_delete(ctx):
        _call(ctx, "POST", "/api/flow/flow/stopjobs", {"flowName": flow_name})
        r = _call(ctx, "POST", "/api/flow/flow/delete", {"flowName": flow_name})
        assert r.get("deleted") is True, r

    return sc


def schema_and_query(
    base_url: str,
    flow_name: str = "probe-query",
    token: Optional[str] = None,
) -> Scenario:
    """Infer schema from sampled events -> create kernel -> execute a
    query -> recycle (reference: InteractiveQueryAndSchemaGenScenarios)."""
    sc = Scenario("SchemaAndQuery")

    @sc.step
    def init_context(ctx):
        ctx.setdefault("base_url", base_url)
        ctx.setdefault("token", token)

    @sc.step
    def infer_schema(ctx):
        events = [{"deviceId": i % 3, "temperature": 10.0 * i} for i in range(20)]
        r = _call(ctx, "POST", "/api/schemainference/inputdata/inferschema",
                  {"name": flow_name, "events": events})
        schema = r.get("Schema") or r.get("schema")
        assert schema, r
        ctx["schema"] = schema if isinstance(schema, str) else json.dumps(schema)

    @sc.step
    def create_kernel(ctx):
        r = _call(ctx, "POST", "/api/interactivequery/kernel",
                  {"name": flow_name, "inputSchema": ctx["schema"]})
        assert r.get("kernelId"), r
        ctx["kernelId"] = r["kernelId"]

    @sc.step
    def execute_query(ctx):
        r = _call(ctx, "POST", "/api/interactivequery/kernel/executequery", {
            "kernelId": ctx["kernelId"],
            "query": "--DataXQuery--\nT = SELECT deviceId, "
                     "COUNT(*) AS c FROM DataXProcessedInput GROUP BY deviceId",
            "maxRows": 10,
        })
        assert r.get("result"), r

    @sc.step
    def recycle_kernel(ctx):
        r = _call(ctx, "POST", "/api/interactivequery/kernel/delete",
                  {"kernelId": ctx["kernelId"]})
        assert r.get("deleted") is True, r

    return sc


def default_suite(base_url: str, token: Optional[str] = None):
    """The JobRunner's standing probe set (DataXDeployJob analog)."""
    return [
        save_and_deploy(base_url, token=token),
        schema_and_query(base_url, token=token),
    ]
