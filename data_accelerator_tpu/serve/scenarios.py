"""Canned e2e scenario suites over the live REST API.

reference: Tests/DataXScenarios/{SaveAndDeploy,
InteractiveQueryAndSchemaGenScenarios}.cs — [Step]-attributed HTTP
sequences sharing a ScenarioContext, run by ScenarioTester against a
deployed instance and scheduled continuously by Services/JobRunner as
the production liveness probe.

Each builder returns a Scenario whose steps hit the given base URL
(website or gateway; pass a bearer token for the gateway). Wire into
JobRunner for the scheduled-probe role:

    runner = JobRunner([save_and_deploy(url), schema_and_query(url)])
    runner.start()
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Optional

from .scenario import Scenario, ScenarioContext

_SCHEMA = json.dumps({"type": "struct", "fields": [
    {"name": "deviceId", "type": "long", "nullable": False,
     "metadata": {"allowedValues": [1, 2, 3]}},
    {"name": "temperature", "type": "double", "nullable": False,
     "metadata": {"minValue": 0, "maxValue": 100}},
]})


def probe_deploy_gui(flow_name: str = "probe-deploy") -> dict:
    """The SaveAndDeploy probe's flow config — module-level so the
    analyzer self-lint (tests/test_analysis.py) can assert the shipped
    probe stays diagnostics-clean."""
    return {
        "name": flow_name,
        "displayName": "Probe Deploy",
        "input": {"mode": "streaming", "type": "local", "properties": {
            "inputSchemaFile": _SCHEMA,
            "normalizationSnippet": "Raw.*",
        }},
        "process": {"queries": [
            "--DataXQuery--\n"
            "Hot = SELECT deviceId, temperature FROM DataXProcessedInput "
            "WHERE temperature > 50;\n"
            "OUTPUT Hot TO HotConsole;"
        ]},
        "outputs": [{"id": "HotConsole", "type": "console",
                     "properties": {}}],
    }


def shipped_flow_guis() -> list:
    """Every flow config this module ships — the analyzer self-lint
    surface (all must produce zero error diagnostics)."""
    return [probe_deploy_gui()]


def _call(ctx: ScenarioContext, method: str, path: str, body=None):
    url = f"{ctx['base_url'].rstrip('/')}{path}"
    headers = {"Content-Type": "application/json"}
    if ctx.get("token"):
        headers["Authorization"] = f"Bearer {ctx['token']}"
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode() if body is not None else None,
        headers=headers,
        method=method,
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        payload = json.loads(r.read() or b"{}")
    return payload.get("result", payload)


def save_and_deploy(
    base_url: str,
    flow_name: str = "probe-deploy",
    token: Optional[str] = None,
    batches: int = 2,
) -> Scenario:
    """Save flow -> generate configs -> start -> jobs running -> stop ->
    delete (reference: SaveAndDeploy.cs over FlowManagementController)."""
    sc = Scenario(f"SaveAndDeploy")

    @sc.step
    def init_context(ctx):
        ctx.setdefault("base_url", base_url)
        ctx.setdefault("token", token)

    @sc.step
    def save_flow(ctx):
        r = _call(ctx, "POST", "/api/flow/flow/save",
                  probe_deploy_gui(flow_name))
        assert r.get("name") == flow_name, r

    @sc.step
    def generate_configs(ctx):
        r = _call(ctx, "POST", "/api/flow/flow/generateconfigs",
                  {"flowName": flow_name})
        assert r.get("jobNames"), r
        ctx["jobNames"] = r["jobNames"]

    @sc.step
    def start_jobs(ctx):
        r = _call(ctx, "POST", "/api/flow/flow/startjobs",
                  {"flowName": flow_name, "batches": batches})
        assert len(r) == len(ctx["jobNames"]), r

    @sc.step
    def jobs_reach_terminal_state(ctx):
        deadline = time.time() + 60
        states = {}
        while time.time() < deadline:
            jobs = _call(ctx, "POST", "/api/flow/job/getbynames",
                         {"jobNames": ctx["jobNames"]})
            states = {j["name"]: j.get("state") for j in jobs if j}
            if states and all(s in ("idle", "success")
                              for s in states.values()):
                return  # finite-batch run completed
            if any(s == "error" for s in states.values()):
                raise AssertionError(f"job failed: {states}")
            _call(ctx, "POST", "/api/flow/job/syncall", {})
            time.sleep(1)
        raise AssertionError(f"jobs never settled: {states}")

    @sc.step
    def stop_and_delete(ctx):
        _call(ctx, "POST", "/api/flow/flow/stopjobs", {"flowName": flow_name})
        r = _call(ctx, "POST", "/api/flow/flow/delete", {"flowName": flow_name})
        assert r.get("deleted") is True, r

    return sc


def schema_and_query(
    base_url: str,
    flow_name: str = "probe-query",
    token: Optional[str] = None,
) -> Scenario:
    """Infer schema from sampled events -> create kernel -> execute a
    query -> recycle (reference: InteractiveQueryAndSchemaGenScenarios)."""
    sc = Scenario("SchemaAndQuery")

    @sc.step
    def init_context(ctx):
        ctx.setdefault("base_url", base_url)
        ctx.setdefault("token", token)

    @sc.step
    def infer_schema(ctx):
        events = [{"deviceId": i % 3, "temperature": 10.0 * i} for i in range(20)]
        r = _call(ctx, "POST", "/api/schemainference/inputdata/inferschema",
                  {"name": flow_name, "events": events})
        schema = r.get("Schema") or r.get("schema")
        assert schema, r
        ctx["schema"] = schema if isinstance(schema, str) else json.dumps(schema)

    @sc.step
    def create_kernel(ctx):
        r = _call(ctx, "POST", "/api/interactivequery/kernel",
                  {"name": flow_name, "inputSchema": ctx["schema"]})
        assert r.get("kernelId"), r
        ctx["kernelId"] = r["kernelId"]

    @sc.step
    def execute_query(ctx):
        r = _call(ctx, "POST", "/api/interactivequery/kernel/executequery", {
            "kernelId": ctx["kernelId"],
            "query": "--DataXQuery--\nT = SELECT deviceId, "
                     "COUNT(*) AS c FROM DataXProcessedInput GROUP BY deviceId",
            "maxRows": 10,
        })
        assert r.get("result"), r

    @sc.step
    def recycle_kernel(ctx):
        r = _call(ctx, "POST", "/api/interactivequery/kernel/delete",
                  {"kernelId": ctx["kernelId"]})
        assert r.get("deleted") is True, r

    return sc


def default_suite(base_url: str, token: Optional[str] = None):
    """The JobRunner's standing probe set (DataXDeployJob analog)."""
    return [
        save_and_deploy(base_url, token=token),
        schema_and_query(base_url, token=token),
    ]


# ---------------------------------------------------------------------------
# Chaos scenario suite (ROADMAP item 5): in-process fault drills over a
# live StreamingHost, each asserting exactly-once-per-window recovery
# with the pilot DISABLED (baseline survives) and — pilot ENABLED —
# additionally that the expected actuation fired (pilot/chaos.py holds
# the injectors; the tier-1 suite runs these at depth 2).
# ---------------------------------------------------------------------------
_CHAOS_SCHEMA = json.dumps({"type": "struct", "fields": [
    {"name": "k", "type": "long", "nullable": False, "metadata": {}},
    {"name": "v", "type": "double", "nullable": False, "metadata": {}},
    {"name": "seq", "type": "long", "nullable": False, "metadata": {}},
]})

_CHAOS_TRANSFORM = (
    "--DataXQuery--\n"
    "Out = SELECT k, v, seq FROM DataXProcessedInput\n"
    "--DataXQuery--\n"
    "Hot = SELECT k, COUNT(*) AS c FROM DataXProcessedInput GROUP BY k\n"
)


def _chaos_events(n: int) -> list:
    return [{"k": i % 4, "v": float(i), "seq": i} for i in range(n)]


def _chaos_payload(rows) -> bytes:
    return b"".join(json.dumps(r).encode() + b"\n" for r in rows)


def _build_chaos_host(ctx, name: str, pilot: bool, depth: int = 2,
                      pilot_conf: Optional[dict] = None,
                      reuse_source: bool = False):
    """One socket-fed StreamingHost with a RecordingSink on ``Out`` —
    the shared fixture every chaos scenario drills. ``ctx['workdir']``
    is the only required input. ``reuse_source`` rebuilds the host over
    the surviving source (the preemption-recovery 'new process')."""
    import os

    from ..core.config import SettingDictionary
    from ..pilot.chaos import RecordingSink
    from ..runtime.host import StreamingHost
    from ..runtime.sources import SocketSource

    workdir = ctx["workdir"]
    tpath = os.path.join(workdir, "chaos.transform")
    if not os.path.exists(tpath):
        with open(tpath, "w", encoding="utf-8") as f:
            f.write(_CHAOS_TRANSFORM)
    conf = {
        "datax.job.name": name,
        "datax.job.input.default.blobschemafile": _CHAOS_SCHEMA,
        "datax.job.input.default.eventhub.maxrate": "4",
        "datax.job.input.default.eventhub.checkpointdir": os.path.join(
            workdir, "ckpt"
        ),
        "datax.job.input.default.eventhub.checkpointinterval": "0 second",
        "datax.job.input.default.streaming.intervalinseconds": "1",
        "datax.job.process.transform": tpath,
        "datax.job.process.batchcapacity": "8",
        "datax.job.process.pipeline.depth": str(depth),
        # every chaos drill runs with the buffer sanitizer armed: the
        # crash/rescale/outage churn is the exact regime where an
        # escaped pooled/donated view would surface, and the drills
        # assert it stays silent (zero DX805 poison hits)
        "datax.job.process.debug.buffersanitizer": "true",
        # ... and with the protocol monitor armed: the same churn is
        # where an ack-before-durability reorder would surface, and
        # the drills assert zero DX906 protocol violations
        "datax.job.process.debug.protocolmonitor": "true",
        "datax.job.process.telemetry.tracefile": os.path.join(
            workdir, "trace.jsonl"
        ),
        "datax.job.output.Out.console.maxrows": "0",
        "datax.job.output.Hot.console.maxrows": "0",
    }
    if pilot:
        # tight loop knobs so evaluation windows elapse inside a drill
        conf.update({
            "datax.job.process.pilot.windowseconds": "0.02",
            "datax.job.process.pilot.cooldownseconds": "0.02",
            "datax.job.process.observability.stallewmams": "200",
        })
        for k, v in (pilot_conf or {}).items():
            conf[f"datax.job.process.pilot.{k}"] = str(v)
    else:
        conf["datax.job.process.pilot.enabled"] = "false"
    if reuse_source and ctx.get("src") is not None:
        src = ctx["src"]
    else:
        src = SocketSource(port=0)
    host = StreamingHost(SettingDictionary(conf), source=src)
    sink = RecordingSink()
    host.dispatcher.operators["Out"].sinks = [sink]
    # the grouped (hot-key) output records too — keeps the drill
    # assertable and the console quiet; only Out carries the
    # exactly-once witness (per-event seq)
    ctx["hot_sink"] = RecordingSink()
    host.dispatcher.operators["Hot"].sinks = [ctx["hot_sink"]]
    ctx["host"], ctx["src"], ctx["sink"] = host, src, sink
    ctx.setdefault("sinks", []).append(sink)
    ctx["tracefile"] = conf["datax.job.process.telemetry.tracefile"]
    return host


def _delivered(ctx) -> list:
    return [
        seq for sink in ctx.get("sinks", []) for seq in sink.values("seq")
    ]


def _assert_exactly_once(ctx, n: int) -> None:
    seqs = _delivered(ctx)
    assert sorted(seqs) == list(range(n)), (
        f"exactly-once violated: {len(seqs)} deliveries of {n} events; "
        f"dupes/losses over {sorted(set(range(n)) ^ set(seqs))[:10]}"
    )


def _assert_pilot_reacted(ctx, action: str, host=None) -> None:
    """Pilot-on acceptance: the expected actuation fired, the
    Pilot_Actuations_Count series is > 0, and the actuation is visible
    as a ``pilot/decide`` span in the flight recorder. ``host``
    overrides ``ctx['host']`` for drills that rotate hosts (the
    rescale handoff asserts against the PREDECESSOR's pilot)."""
    host = host if host is not None else ctx["host"]
    pilot = host.pilot
    assert pilot is not None
    applied = [
        d for d in ctx.get("applied_decisions", [])
        if d.applied and d.action == action
    ]
    assert applied, (
        f"no applied '{action}' actuation; decisions="
        f"{[(d.rule, d.action, d.suppressed) for d in ctx.get('applied_decisions', [])]}"
    )
    pts = host.metric_logger.store.points(
        host.metric_logger.key("Pilot_Actuations_Count")
    )
    assert pts and float(pts[-1]["val"]) > 0, "Pilot_Actuations_Count not > 0"
    spans = []
    with open(ctx["tracefile"], encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("type") == "span" and rec.get("name") == "pilot/decide":
                spans.append(rec)
    acted = [
        s for s in spans
        if s.get("properties", {}).get("applied")
        and s["properties"].get("action") == action
    ]
    assert acted, f"no applied pilot/decide span for '{action}'"


def _drain(ctx, host, expect_total: int, chunk: int = 4,
           timeout_s: float = 30.0):
    """Run the pipelined loop in chunks until every expected event has
    landed (backpressure may shrink polls, so a fixed batch count can't
    know when the stream is drained), accumulating every pilot
    decision along the way. If the drain finished before an evaluation
    window ever elapsed, evaluate once directly — the signals (all
    EWMAs) are still live; only the wall-clock cadence is forced."""
    collected = ctx.setdefault("applied_decisions", [])
    pilot = host.pilot
    orig_evaluate = pilot.evaluate if pilot is not None else None

    def evaluate(*a, **k):
        ds = orig_evaluate(*a, **k)
        collected.extend(ds)
        return ds

    if pilot is not None:
        pilot.evaluate = evaluate
    try:
        deadline = time.time() + timeout_s
        while len(_delivered(ctx)) < expect_total:
            # max_batches counts batches over the host's LIFETIME, so
            # each chunk extends the allowance past what's done
            host.run_pipelined(
                max_batches=host.batches_processed + chunk
            )
            if time.time() > deadline:
                raise AssertionError(
                    f"drain timed out: {len(_delivered(ctx))}/"
                    f"{expect_total} delivered"
                )
        if pilot is not None and not any(d.applied for d in collected):
            evaluate()
    finally:
        if pilot is not None:
            pilot.evaluate = orig_evaluate


def chaos_preemption(pilot: bool = False, depth: int = 2) -> Scenario:
    """Job kill/restart mid-window: the 3rd dispatch dies with batches
    in flight (TPU preemption analog), a fresh host over the same
    checkpoint dir + requeued source recovers, and every event lands
    exactly once. Pilot-on: the recovery backlog saturates ingest at
    the depth ceiling -> the pilot asks for a replica
    (``rescale-up`` through a ScaleActuator, vetted path)."""
    sc = Scenario(f"ChaosPreemption{'Pilot' if pilot else ''}")
    n_events = 32

    @sc.step
    def build_host(ctx):
        _build_chaos_host(
            ctx, "ChaosPreemptP" if pilot else "ChaosPreemptB", pilot, depth,
            # cap depth so sustained saturation escalates to rescale
            pilot_conf={"maxdepth": depth, "saturationhigh": "0.5"},
        )

    @sc.step
    def feed_events(ctx):
        from ..pilot.chaos import feed_socket

        feed_socket(ctx["src"], _chaos_payload(_chaos_events(n_events)),
                    expect_events=n_events)

    @sc.step
    def preempt_mid_window(ctx):
        from ..pilot.chaos import ChaosFault, PreemptionInjector

        inj = PreemptionInjector(kill_at_dispatch=3)
        inj.arm(ctx["host"])
        try:
            ctx["host"].run_pipelined(max_batches=n_events // 4)
        except ChaosFault:
            ctx["preempted"] = True
        finally:
            inj.disarm()
            # the 'killed process': tear down without closing the
            # source — the successor host takes it over
            ctx["host"].stop(close_sources=False)
        assert ctx.get("preempted"), "injector never fired"

    @sc.step
    def recover_with_fresh_host(ctx):
        from ..pilot.controller import ScaleActuator
        from ..pilot.chaos import RecordingRescaler

        ctx["src"].requeue_unacked()
        host = _build_chaos_host(
            ctx, "ChaosPreemptP" if pilot else "ChaosPreemptB", pilot, depth,
            pilot_conf={"maxdepth": depth, "saturationhigh": "0.5"},
            reuse_source=True,
        )
        if pilot and host.pilot is not None:
            scaler = ctx["scaler"] = RecordingRescaler()
            act = ScaleActuator(scaler, "ChaosPreempt", max_replicas=4)
            for kind in act.kinds:
                host.pilot.actuators[kind] = act
        _drain(ctx, host, n_events)
        host.stop()

    @sc.step
    def assert_recovered_exactly_once(ctx):
        _assert_exactly_once(ctx, n_events)

    if pilot:
        @sc.step
        def assert_pilot_rescaled(ctx):
            _assert_pilot_reacted(ctx, "rescale-up")
            assert ctx["scaler"].calls and ctx["scaler"].calls[0] >= 2

    return sc


def chaos_sink_outage(pilot: bool = False, depth: int = 2) -> Scenario:
    """Sink outage: a hard outage mid-window fails the batch — the
    whole un-acked window requeues (FIFO commit holds) — then the sink
    comes back SLOW (brown-out): landings queue behind the dispatch
    loop and, pilot-on, the landing-backlog signal engages source
    backpressure (the token bucket shrinks polls)."""
    sc = Scenario(f"ChaosSinkOutage{'Pilot' if pilot else ''}")
    n_events = 24

    @sc.step
    def build_host(ctx):
        _build_chaos_host(ctx, "ChaosSinkP" if pilot else "ChaosSinkB", pilot, depth,
                          pilot_conf={"backloghigh": "2"})

    @sc.step
    def feed_events(ctx):
        from ..pilot.chaos import feed_socket

        feed_socket(ctx["src"], _chaos_payload(_chaos_events(n_events)),
                    expect_events=n_events)

    @sc.step
    def hard_outage_requeues_window(ctx):
        from ..pilot.chaos import ChaosFault, SinkOutageInjector

        inj = SinkOutageInjector(fail=True)
        inj.arm(ctx["host"])
        try:
            ctx["host"].run_pipelined(max_batches=n_events // 4)
        except ChaosFault:
            ctx["outage_hit"] = True
        finally:
            inj.disarm()
        assert ctx.get("outage_hit"), "outage never hit a write"
        ctx["src"].requeue_unacked()

    @sc.step
    def brownout_recovery(ctx):
        from ..pilot.chaos import SinkOutageInjector

        inj = SinkOutageInjector(delay_s=0.08)
        inj.arm(ctx["host"])
        try:
            _drain(ctx, ctx["host"], n_events)
        finally:
            inj.disarm()
            ctx["host"].stop()

    @sc.step
    def assert_recovered_exactly_once(ctx):
        _assert_exactly_once(ctx, n_events)

    if pilot:
        @sc.step
        def assert_pilot_backpressured(ctx):
            _assert_pilot_reacted(ctx, "backpressure")

    return sc


def chaos_hot_key_skew(pilot: bool = False, depth: int = 2) -> Scenario:
    """Hot-key skew: 90% of events hammer one group key and the device
    step slows under the serialized hot group (DeviceSlowdownInjector
    models the skewed groupby scan) — the dispatch loop stalls on the
    window's oldest batch. Pilot-on: the smoothed stall (the SAME
    conf'd EWMA /readyz judges) crosses ``stallhighms`` and the pilot
    drops pipeline depth, draining the window FIFO-first."""
    sc = Scenario(f"ChaosHotKeySkew{'Pilot' if pilot else ''}")
    n_events = 32

    @sc.step
    def build_host(ctx):
        _build_chaos_host(ctx, "ChaosSkewP" if pilot else "ChaosSkewB", pilot, depth,
                          pilot_conf={"stallhighms": "20"})

    @sc.step
    def feed_skewed_events(ctx):
        from ..pilot.chaos import feed_socket, skewed_events

        rows = skewed_events(n_events, hot_key=0, hot_fraction=0.9)
        feed_socket(ctx["src"], _chaos_payload(rows),
                    expect_events=n_events)

    @sc.step
    def run_under_skew(ctx):
        from ..pilot.chaos import DeviceSlowdownInjector

        inj = DeviceSlowdownInjector(extra_s=0.06)
        inj.arm(ctx["host"])
        try:
            _drain(ctx, ctx["host"], n_events)
        finally:
            inj.disarm()
            ctx["host"].stop()

    @sc.step
    def assert_exactly_once_under_skew(ctx):
        _assert_exactly_once(ctx, n_events)

    if pilot:
        @sc.step
        def assert_pilot_dropped_depth(ctx):
            _assert_pilot_reacted(ctx, "depth-down")
            assert ctx["host"].live_depth() < depth, (
                f"depth still {ctx['host'].live_depth()}"
            )

    return sc


def chaos_malformed_flood(pilot: bool = False, depth: int = 2) -> Scenario:
    """Malformed-input flood: half the stream is garbage (truncated
    JSON, binary noise). The decoders skip bad lines — every VALID
    event still lands exactly once — and, pilot-on, the malformed-rate
    signal engages backpressure so the host stops burning batch
    capacity decoding garbage at full rate."""
    sc = Scenario(f"ChaosMalformedFlood{'Pilot' if pilot else ''}")
    n_valid = 16

    @sc.step
    def build_host(ctx):
        _build_chaos_host(ctx, "ChaosFloodP" if pilot else "ChaosFloodB", pilot, depth,
                          pilot_conf={"malformedhigh": "0.3"})

    @sc.step
    def feed_flood(ctx):
        from ..pilot.chaos import feed_socket, malformed_payload

        payload = malformed_payload(
            _chaos_events(n_valid), flood_ratio=0.5
        )
        ctx["total_lines"] = payload.count(b"\n")
        feed_socket(ctx["src"], payload,
                    expect_events=ctx["total_lines"])

    @sc.step
    def run_through_flood(ctx):
        _drain(ctx, ctx["host"], n_valid)
        ctx["host"].stop()

    @sc.step
    def assert_valid_events_exactly_once(ctx):
        _assert_exactly_once(ctx, n_valid)

    if pilot:
        @sc.step
        def assert_pilot_backpressured(ctx):
            _assert_pilot_reacted(ctx, "backpressure")

    return sc


# ---------------------------------------------------------------------------
# Rescale-with-state chaos drill (the elastic stateful rescale proof):
# a stateful TIMEWINDOW + accumulator flow is rescaled MID-WINDOW —
# up (1 -> 2 replicas) then down (2 -> 1) — with a snapshot corruption
# injected between predecessor stop and successor load. Every event
# must land exactly once ACROSS the whole replica lineage, partitioned
# state must follow the replicas through the objstore mirror, and the
# corrupted partition must recover via the standby side (DX530).
# ---------------------------------------------------------------------------
_STATE_SCHEMA = json.dumps({"type": "struct", "fields": [
    {"name": "k", "type": "long", "nullable": False, "metadata": {}},
    {"name": "v", "type": "double", "nullable": False, "metadata": {}},
    {"name": "seq", "type": "long", "nullable": False, "metadata": {}},
]})

_STATE_TRANSFORM = (
    "--DataXQuery--\n"
    "merged = SELECT k, v FROM DataXProcessedInput "
    "UNION ALL SELECT k, v FROM seen\n"
    "--DataXQuery--\n"
    "seen = SELECT k, MAX(v) AS v FROM merged GROUP BY k\n"
    "--DataXQuery--\n"
    "Out = SELECT k, v, seq FROM DataXProcessedInput\n"
    "--DataXQuery--\n"
    "Win = SELECT k, COUNT(*) AS c "
    "FROM DataXProcessedInput_30seconds GROUP BY k\n"
)

_STATE_KEYS = 8
_STATE_PARTS = 8


def _state_events(lo: int, hi: int) -> list:
    return [
        {"k": i % _STATE_KEYS, "v": float(i), "seq": i}
        for i in range(lo, hi)
    ]


def _build_stateful_host(ctx, name: str, pilot: bool, depth: int,
                         replica_index: int = 1, replica_count: int = 1,
                         gen: int = 0, pilot_conf: Optional[dict] = None,
                         src=None):
    """One socket-fed stateful host: TIMEWINDOW ring + `seen` MAX
    accumulator, state hashed onto ``_STATE_PARTS`` key-range
    partitions mirrored through the scenario's live object store.
    ``gen`` isolates checkpoint/state dirs per host INSTANCE, so a
    successor's only route to predecessor state is the partition
    handoff through the mirror — exactly the cross-host shape."""
    import os

    from ..core.config import SettingDictionary
    from ..pilot.chaos import RecordingSink
    from ..runtime.host import StreamingHost
    from ..runtime.sources import SocketSource

    workdir = ctx["workdir"]
    tpath = os.path.join(workdir, "state.transform")
    if not os.path.exists(tpath):
        with open(tpath, "w", encoding="utf-8") as f:
            f.write(_STATE_TRANSFORM)
    hostdir = os.path.join(workdir, f"g{gen}-r{replica_index}")
    conf = {
        "datax.job.name": name,
        "datax.job.input.default.blobschemafile": _STATE_SCHEMA,
        "datax.job.input.default.eventhub.maxrate": "4",
        "datax.job.input.default.eventhub.checkpointdir": os.path.join(
            hostdir, "ckpt"
        ),
        "datax.job.input.default.eventhub.checkpointinterval": "0 second",
        "datax.job.input.default.streaming.intervalinseconds": "1",
        "datax.job.process.timestampcolumn": "ts",
        "datax.job.process.watermark": "0 second",
        "datax.job.process.transform": tpath,
        "datax.job.process.batchcapacity": "8",
        "datax.job.process.pipeline.depth": str(depth),
        "datax.job.process.timewindow.DataXProcessedInput_30seconds"
        ".windowduration": "30 seconds",
        "datax.job.process.statetable.seen.schema": "k long, v double",
        "datax.job.process.statetable.seen.location": os.path.join(
            hostdir, "state", "seen"
        ),
        "datax.job.process.state.partitions": str(_STATE_PARTS),
        "datax.job.process.state.partitionkey": "k",
        "datax.job.process.state.replicaindex": str(replica_index),
        "datax.job.process.state.replicacount": str(replica_count),
        "datax.job.process.state.snapshoturl": ctx["store_url"],
        "datax.job.process.state.filteringest": "true",
        # fleet telemetry plane: every drill host publishes frames to
        # the scenario's live store (windowseconds=0 -> one frame per
        # batch), so the rescale lineage is observable as ONE fleet
        # series across generations
        "datax.job.process.fleet.publishurl": ctx["store_url"],
        "datax.job.process.fleet.windowseconds": "0",
        "datax.job.process.fleet.replica": f"g{gen}-r{replica_index}",
        # every drill runs with the DX805 buffer sanitizer armed: the
        # rescale handoff churn must not leak a pooled/donated view
        "datax.job.process.debug.buffersanitizer": "true",
        # ... and the DX906 protocol monitor: the successor host must
        # hold the delivery ordering batch by batch too
        "datax.job.process.debug.protocolmonitor": "true",
        "datax.job.process.telemetry.tracefile": os.path.join(
            workdir, "trace.jsonl"
        ),
        "datax.job.output.Out.console.maxrows": "0",
        "datax.job.output.Win.console.maxrows": "0",
    }
    if pilot:
        conf.update({
            "datax.job.process.pilot.windowseconds": "0.02",
            "datax.job.process.pilot.cooldownseconds": "0.02",
            "datax.job.process.observability.stallewmams": "200",
        })
        for k, v in (pilot_conf or {}).items():
            conf[f"datax.job.process.pilot.{k}"] = str(v)
    else:
        conf["datax.job.process.pilot.enabled"] = "false"
    if src is None:
        src = SocketSource(port=0)
    host = StreamingHost(SettingDictionary(conf), source=src)
    sink = RecordingSink()
    host.dispatcher.operators["Out"].sinks = [sink]
    host.dispatcher.operators["Win"].sinks = [RecordingSink()]
    ctx["host"], ctx["src"], ctx["sink"] = host, src, sink
    ctx.setdefault("sinks", []).append(sink)
    ctx["tracefile"] = conf["datax.job.process.telemetry.tracefile"]
    return host


def _drain_remaining_payload(src) -> bytes:
    """Everything a stopped predecessor's source still holds —
    requeued un-acked batches plus never-polled buffer — as one raw
    payload (the events a key-routed rebalance re-delivers)."""
    src.requeue_unacked()
    chunks = []
    while True:
        blob, n, _offsets = src.poll_raw(1000)
        if n == 0:
            break
        chunks.append(blob)
    src.close()
    return b"".join(chunks)


def _drain_group(ctx, hosts, expect_total: int, chunk: int = 2,
                 timeout_s: float = 60.0) -> None:
    """Run a replica GROUP in round-robin chunks until every expected
    event has landed across the lineage's sinks."""
    deadline = time.time() + timeout_s
    while len(_delivered(ctx)) < expect_total:
        for h in hosts:
            h.run_pipelined(max_batches=h.batches_processed + chunk)
        if time.time() > deadline:
            raise AssertionError(
                f"group drain timed out: {len(_delivered(ctx))}/"
                f"{expect_total} delivered"
            )


def _loaded_state_map(host) -> dict:
    """The `seen` accumulator a replica PERSISTED (its owned
    partitions), reloaded from disk: {k: max v}."""
    import numpy as np

    t = host.processor.state_tables["seen"].load(host.processor.dictionary)
    return {
        int(k): float(v)
        for k, v, ok in zip(
            np.asarray(t.cols["k"]), np.asarray(t.cols["v"]),
            np.asarray(t.valid),
        ) if ok
    }


def chaos_rescale_with_state(pilot: bool = False, depth: int = 2) -> Scenario:
    """Elastic stateful rescale, chaos-proven: a stateful flow
    (TIMEWINDOW ring + `seen` accumulator on 8 key-range partitions)
    is rescaled mid-window — up to two replicas, later back down to
    one — with every partition's ACTIVE state snapshot corrupted in
    the store between predecessor stop and successor load. Successors
    pull only their assigned partitions (windows merged, accumulators
    reloaded, corruption recovered via the standby side + un-acked
    replay), the key-routed ingest filter splits the remaining stream
    exactly once across the new replica group, and the whole lineage
    delivers every event exactly once. Pilot-on: the predecessor's
    sustained saturation drives a ``rescale-up`` actuation through the
    vetted ScaleActuator path before the handoff."""
    sc = Scenario(f"ChaosRescaleState{'Pilot' if pilot else ''}")
    n_pre = 24    # events fed to the predecessor
    n_tail = 8    # events fed after the scale-down
    expected_final = {k: float(24 + k) for k in range(_STATE_KEYS)}

    @sc.step
    def start_store(ctx):
        from .objectstore import ObjectStoreServer

        store = ObjectStoreServer(port=0).start()  # in-memory
        ctx["store"] = store
        scn = f"rescale-{'p' if pilot else 'b'}-d{depth}"
        ctx["store_url"] = (
            f"objstore://127.0.0.1:{store.port}/chaos/{scn}"
        )

    @sc.step
    def build_predecessor(ctx):
        _build_stateful_host(
            ctx, "RescaleStateP" if pilot else "RescaleStateB", pilot,
            depth, gen=0,
            pilot_conf={"maxdepth": depth, "saturationhigh": "0.5"},
        )

    @sc.step
    def feed_events(ctx):
        from ..pilot.chaos import feed_socket

        feed_socket(ctx["src"], _chaos_payload(_state_events(0, n_pre)),
                    expect_events=n_pre)

    @sc.step
    def run_until_mid_window(ctx):
        host = ctx["host"]
        collected = ctx.setdefault("applied_decisions", [])
        scaler = None
        if pilot and host.pilot is not None:
            from ..pilot.chaos import RecordingRescaler
            from ..pilot.controller import ScaleActuator

            scaler = ctx["scaler"] = RecordingRescaler()
            act = ScaleActuator(scaler, "RescaleState", max_replicas=4)
            for kind in act.kinds:
                host.pilot.actuators[kind] = act
            orig_evaluate = host.pilot.evaluate

            def evaluate(*a, **k):
                ds = orig_evaluate(*a, **k)
                collected.extend(ds)
                return ds

            host.pilot.evaluate = evaluate
        # a few batches into the 30 s window, then 'preempt' for the
        # rescale: well under n_pre events processed — state + window
        # rings hold committed history the successors must inherit
        host.run_pipelined(max_batches=host.batches_processed + 3)
        if pilot and host.pilot is not None and not any(
            d.applied and d.action == "rescale-up" for d in collected
        ):
            host.pilot.evaluate()
        ctx["pilot_host"] = host
        ctx["pre_delivered"] = len(_delivered(ctx))
        assert 0 < ctx["pre_delivered"] < n_pre, ctx["pre_delivered"]
        host.stop(close_sources=False)

    @sc.step
    def corrupt_partitions_mid_handoff(ctx):
        from ..pilot.chaos import PartitionLossInjector

        inj = PartitionLossInjector(
            store_url=ctx["store_url"], table="seen", mode="truncate",
        )
        assert inj.corrupt(), "no active state snapshots to corrupt"
        ctx["corrupted"] = inj.corrupted

    @sc.step
    def rescale_up_handoff(ctx):
        payload = _drain_remaining_payload(ctx["src"])
        name = "RescaleStateP" if pilot else "RescaleStateB"
        b1 = _build_stateful_host(ctx, name, pilot=False, depth=depth,
                                  replica_index=1, replica_count=2, gen=1)
        src1 = ctx["src"]
        b2 = _build_stateful_host(ctx, name, pilot=False, depth=depth,
                                  replica_index=2, replica_count=2, gen=1)
        src2 = ctx["src"]
        ctx["successors"] = [b1, b2]
        # the successors inherited the windows through the partition
        # handoff (fresh local dirs — the mirror was the only route)
        assert b1.window_restored_from == "partitions", (
            b1.window_restored_from
        )
        assert b2.window_restored_from == "partitions", (
            b2.window_restored_from
        )
        # the corrupted active sides were recovered via standby (DX530)
        fallbacks = (
            b1.processor.state_stats.get("LoadFallback_Count", 0)
            + b2.processor.state_stats.get("LoadFallback_Count", 0)
        )
        assert fallbacks >= 1, "corruption never hit the loaders"
        # BOTH successors get the FULL remaining stream; the key-routed
        # ingest filter must split it exactly once across the group
        from ..pilot.chaos import feed_socket

        n_lines = payload.count(b"\n")
        if n_lines:
            feed_socket(src1, payload, expect_events=n_lines)
            feed_socket(src2, payload, expect_events=n_lines)
        _drain_group(ctx, [b1, b2], n_pre)
        for h in (b1, b2):
            h.stop()

    @sc.step
    def assert_scaled_up_exactly_once(ctx):
        _assert_exactly_once(ctx, n_pre)
        # partitioned accumulators followed the replicas: the merged
        # owned-partition state of the group equals the full-stream MAX
        merged = {}
        for h in ctx["successors"]:
            merged.update(_loaded_state_map(h))
        expect = {k: float(16 + k) for k in range(_STATE_KEYS)}
        assert merged == expect, f"state diverged: {merged} != {expect}"

    @sc.step
    def rescale_down_handoff(ctx):
        from ..pilot.chaos import feed_socket

        name = "RescaleStateP" if pilot else "RescaleStateB"
        c = _build_stateful_host(ctx, name, pilot=False, depth=depth,
                                 replica_index=1, replica_count=1, gen=2)
        # scale-down merge: C's windows come from TWO predecessors'
        # partition pushes (re-packed per slot, bases rebased)
        assert c.window_restored_from == "partitions", (
            c.window_restored_from
        )
        feed_socket(ctx["src"], _chaos_payload(
            _state_events(n_pre, n_pre + n_tail)
        ), expect_events=n_tail)
        _drain_group(ctx, [c], n_pre + n_tail)
        ctx["final_host"] = c
        c.stop()

    @sc.step
    def assert_final_exactly_once_and_state(ctx):
        _assert_exactly_once(ctx, n_pre + n_tail)
        final = _loaded_state_map(ctx["final_host"])
        assert final == expected_final, (
            f"final state diverged: {final} != {expected_final}"
        )

    @sc.step
    def kill_replica_without_drain(ctx):
        # one more generation, killed WITHOUT a drain: its frames are
        # published (balanced ingested==emitted per acked batch) but
        # the final-frame marker is suppressed — the fleet view must
        # call that replica stale (DX542), not lost data (DX540)
        from ..pilot.chaos import feed_socket

        name = "RescaleStateP" if pilot else "RescaleStateB"
        n_kill = 4
        d = _build_stateful_host(ctx, name, pilot=False, depth=depth,
                                 replica_index=1, replica_count=1, gen=3)
        feed_socket(ctx["src"], _chaos_payload(_state_events(
            n_pre + n_tail, n_pre + n_tail + n_kill
        )), expect_events=n_kill)
        _drain_group(ctx, [d], n_pre + n_tail + n_kill)
        assert d.fleet_publisher is not None, "fleet publisher not armed"
        assert d.fleet_publisher.frames_published >= 1, (
            "killed replica never published a frame"
        )
        d.fleet_publisher.kill()
        d.stop()
        ctx["killed_replica"] = "g3-r1"

    @sc.step
    def assert_fleet_view(ctx):
        # the control-plane aggregation over everything the lineage
        # published: one continuous fleet series (every generation
        # present), delivery conserved end to end, and exactly one
        # stale replica — the undrained kill
        from ..obs.fleetview import FleetView

        name = "RescaleStateP" if pilot else "RescaleStateB"
        view = FleetView(url=ctx["store_url"],
                         now_fn=lambda: time.time() + 60.0)
        assert view.refresh() >= 5, "fewer frames than replicas"
        fm = view.fleet_metrics(name)
        reps = fm["replicas"]
        assert set(reps) == {
            "g0-r1", "g1-r1", "g1-r2", "g2-r1", "g3-r1"
        }, f"lineage not continuous: {sorted(reps)}"
        lin = view.lineage(name)
        assert [seg["replica"] for seg in lin][0] == "g0-r1", lin
        assert len(lin) == 5, lin
        audit = view.audit(name, output="Out")
        counts = audit["counts"]
        assert counts.get("DX540", 0) == 0, f"phantom loss: {audit}"
        assert counts.get("DX541", 0) == 0, f"phantom dup: {audit}"
        assert counts.get("DX542", 0) == 1, f"stale count: {audit}"
        assert audit["conserved"], audit
        total = n_pre + n_tail + 4
        assert audit["ingested"] == total, (audit["ingested"], total)
        assert audit["emitted"].get("Out") == total, audit["emitted"]
        stale = [r for r, s in reps.items() if s["status"] == "stale"]
        assert stale == [ctx["killed_replica"]], stale
        done = [r for r, s in reps.items() if s["status"] == "completed"]
        assert len(done) == 4, reps

    @sc.step
    def stop_store(ctx):
        ctx["store"].stop()

    if pilot:
        @sc.step
        def assert_pilot_rescaled(ctx):
            # the PREDECESSOR ran the pilot (successors spawn unpiloted
            # in this drill); leave it as the context host so generic
            # pilot assertions read the right controller
            ctx["host"] = ctx["pilot_host"]
            _assert_pilot_reacted(ctx, "rescale-up", host=ctx["pilot_host"])
            assert ctx["scaler"].calls and ctx["scaler"].calls[0] >= 2

    return sc


def chaos_suite(pilot: bool = False, depth: int = 2):
    """All five chaos drills (preemption, sink outage, hot-key skew,
    malformed flood, rescale-with-state) — the scenario-diversity
    matrix PILOT.md tables. Each scenario needs a fresh
    ``ScenarioContext`` with a ``workdir``."""
    return [
        chaos_preemption(pilot=pilot, depth=depth),
        chaos_sink_outage(pilot=pilot, depth=depth),
        chaos_hot_key_skew(pilot=pilot, depth=depth),
        chaos_malformed_flood(pilot=pilot, depth=depth),
        chaos_rescale_with_state(pilot=pilot, depth=depth),
    ]
