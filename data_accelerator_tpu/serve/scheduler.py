"""TimedScheduler: background timer driving batch-flow scheduling.

reference: DataX.Flow/DataX.Flow.Scheduler/TimedScheduler.cs:22+ — a
hosted service whose timer periodically calls the management service's
``flow/schedulebatch`` for batch-mode flows that are due. Recurrence
state (what ran last) lives with the scheduler; the per-round work —
regenerate configs for the next window, start jobs — is FlowOperation's
``schedule_batch``.

Schedule conf comes from the flow's gui ``batch`` entries:
``type`` = "oneTime" (run once, then disabled) or "recurring" with
``intervalSeconds``. Missing schedule info on a batching flow means
every scheduler tick is due (the reference's default daily recurrence
plays this role; a tick-gated default keeps one-box demos live).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

logger = logging.getLogger(__name__)


class PlacementReplanner:
    """Re-plans fleet placement whenever jobs start or stop, so freed
    capacity is immediately reusable by the next admission check.

    The counterpart of ``serve/jobs.py``'s ``FleetAdmissionGate``:
    the gate decides *whether* a submit fits; this keeps the persisted
    flow->chip assignments (``JobRegistry`` records' ``placement``) and
    the ``Fleet_*``/``Placement_*`` metrics in step with the set of
    jobs actually running. ``JobOperation`` calls ``on_job_event`` after
    every successful start/stop AND after every in-place
    ``JobOperation.rescale`` (a replica-count change no longer needs a
    stop+start round trip — the rescale path re-runs admission through
    ``FleetAdmissionGate.admit_replicas`` before spawning, then lands
    here so the new replica set's placement persists); ``TimedScheduler``
    additionally calls it each tick so jobs that die on their own
    (crash, batch-mode completion) also release their modeled capacity.
    """

    def __init__(self, gate):
        self.gate = gate
        self.replans = 0

    def on_job_event(self):
        from ..obs import tracing

        # a child span of the active REST request trace when the
        # re-plan was caused by a traced start/stop; a no-op from the
        # scheduler's own tick thread
        with tracing.span("scheduler/replan"):
            report = self.gate.replan()
        self.replans += 1
        try:
            self.gate.metrics.send_metric(
                "Placement_Replans_Count", self.replans
            )
        except Exception:  # noqa: BLE001 — metrics must not fail ops
            logger.exception("placement metric export failed")
        return report


class TimedScheduler:
    def __init__(
        self,
        flow_ops,
        interval_s: float = 60.0,
        now_fn: Callable[[], float] = time.time,
        replanner: Optional[PlacementReplanner] = None,
        fleet_view=None,
    ):
        self.flow_ops = flow_ops
        self.interval_s = interval_s
        self.now = now_fn
        self.replanner = replanner
        # fleet telemetry plane: refresh the cross-replica rollup each
        # tick so /fleet/* routes and the Prometheus rollup serve from
        # a warm aggregate instead of paying the objstore list on read
        self.fleet_view = fleet_view
        # flow name -> batch index -> last run epoch (oneTime: ran at all)
        self._last_run: Dict[str, Dict[int, float]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rounds_triggered = 0

    # -- due computation --------------------------------------------------
    def due_flows(self) -> List[str]:
        """Batching flows with at least one due batch entry."""
        return [name for name, _ in self._due_work()]

    def _due_work(self) -> List[tuple]:
        """(flow name, due batch-entry indices) pairs, one store read."""
        out = []
        for doc in self.flow_ops.get_all_flows():
            gui = doc.get("gui") or {}
            if ((gui.get("input") or {}).get("mode")) != "batching":
                continue
            name = doc.get("name")
            entries = self._due_entries(name, gui)
            if entries:
                out.append((name, entries))
        return out

    def _due_entries(self, name: str, gui: dict) -> List[int]:
        entries = gui.get("batch") or [{}]
        ran = self._last_run.setdefault(name, {})
        now = self.now()
        out = []
        for i, b in enumerate(entries):
            props = (b.get("properties") or {}) if isinstance(b, dict) else {}
            btype = (props.get("type") or b.get("type") or "recurring") \
                if isinstance(b, dict) else "recurring"
            last = ran.get(i)
            if str(btype).lower() == "onetime":
                if last is None:
                    out.append(i)
            else:
                interval = float(
                    props.get("intervalSeconds")
                    or props.get("interval")
                    or self.interval_s
                )
                if last is None or now - last >= interval:
                    out.append(i)
        return out

    # -- tick -------------------------------------------------------------
    def tick(self) -> List[str]:
        """One scheduling pass; returns flows triggered this round."""
        triggered = []
        for name, entries in self._due_work():
            try:
                self.flow_ops.schedule_batch(name)
            except Exception as e:  # noqa: BLE001 — skip flow, keep ticking
                logger.warning("schedulebatch for %s failed: %s", name, e)
                continue
            now = self.now()
            for i in entries:
                self._last_run[name][i] = now
            self.rounds_triggered += 1
            triggered.append(name)
        if self.replanner is not None:
            # jobs that exited on their own since the last tick release
            # their modeled capacity here
            try:
                self.replanner.on_job_event()
            except Exception:  # noqa: BLE001 — scheduler must survive
                logger.exception("scheduled placement re-plan failed")
        if self.fleet_view is not None:
            try:
                self.fleet_view.refresh()
            except Exception:  # noqa: BLE001 — scheduler must survive
                logger.exception("fleet telemetry refresh failed")
        return triggered

    # -- background loop --------------------------------------------------
    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — scheduler must survive
                    logger.exception("scheduler tick failed")

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
