"""Token templating for flow documents.

Two placeholder forms, matching the reference's semantics
(DataX.Config/Templating/{Token,TokenDictionary,TokenReplacement}.cs):

- ``${token}``   — plain token, replaced wherever it appears.
- ``_S_{token}`` — late-bound ("secret") token: resolved only during
  runtime-config generation, so saved flow documents keep the
  placeholder and never embed environment-specific values.

Replacement runs to a fixed point so tokens may expand to strings that
themselves contain tokens (the reference iterates its token list the
same way). A token whose value is a non-string JSON value replaces the
*entire* string when the string is exactly one placeholder — this is how
``"_S_{processTimeWindows}"`` becomes a JSON array in the job config.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional

_PLAIN = "${%s}"
_SECRET = "_S_{%s}"
_TOKEN_RE = re.compile(r"(_S_\{(\w+)\})|(\$\{(\w+)\})")

_MAX_PASSES = 10


class TokenDictionary:
    """Ordered token set with nested-JSON replacement."""

    def __init__(self, tokens: Optional[Dict[str, Any]] = None):
        self._tokens: Dict[str, Any] = dict(tokens or {})

    def set(self, name: str, value: Any) -> None:
        self._tokens[name] = value

    def get(self, name: str, default: Any = None) -> Any:
        return self._tokens.get(name, default)

    def update(self, other: Dict[str, Any]) -> None:
        self._tokens.update(other)

    def names(self):
        return list(self._tokens)

    # -- replacement -----------------------------------------------------
    def _replace_str(self, s: str) -> Any:
        # whole-string single placeholder: may return a non-string value
        m = _TOKEN_RE.fullmatch(s)
        if m:
            name = m.group(2) or m.group(4)
            if name in self._tokens:
                return self._tokens[name]
            return s

        def sub(mm: re.Match) -> str:
            name = mm.group(2) or mm.group(4)
            if name in self._tokens:
                return str(self._tokens[name])
            return mm.group(0)

        return _TOKEN_RE.sub(sub, s)

    def replace(self, value: Any) -> Any:
        """Deep-replace tokens in a nested JSON value, to fixed point."""
        for _ in range(_MAX_PASSES):
            new = self._replace_once(value)
            if new == value:
                return new
            value = new
        return value

    def _replace_once(self, value: Any) -> Any:
        if isinstance(value, str):
            return self._replace_str(value)
        if isinstance(value, dict):
            return {k: self._replace_once(v) for k, v in value.items()}
        if isinstance(value, list):
            return [self._replace_once(v) for v in value]
        return value


def unresolved_tokens(value: Any) -> list:
    """Names of placeholders still present (generation-time validation)."""
    out = []

    def walk(v):
        if isinstance(v, str):
            for m in _TOKEN_RE.finditer(v):
                out.append(m.group(2) or m.group(4))
        elif isinstance(v, dict):
            for x in v.values():
                walk(x)
        elif isinstance(v, list):
            for x in v:
                walk(x)

    walk(value)
    return out
