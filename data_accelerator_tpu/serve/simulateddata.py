"""Simulated-data load generator service.

reference: Services/DataX.SimulatedData/DataX.SimulatedData.DataGenService
— a standing service that synthesizes schema-driven random events plus
periodic *rule-triggering* sequences (DataGen.cs:41-54 GenerateDataRules
interleaves rulesData rows every N batches) and pumps them into the
flow's ingest bus (EventHub/IoTHub/Kafka) at a target rate
(DataGenService.cs send loop).

TPU-native stand-in: events go to the flow's SocketSource ingest port
(the DCN path) as newline JSON. Random rows come from the same
schema-driven DataGenerator the engine's local source uses; rule rows
are explicit templates (dict overlays on a random row) injected every
``rule_period_s`` so alert flows always have something to alert on —
the role rulesData plays for the demo IoT flow.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time
from typing import Dict, List, Optional

from ..core.schema import Schema
from ..utils.datagen import DataGenerator

logger = logging.getLogger(__name__)


class SimulatedDataService:
    def __init__(
        self,
        schema: Schema,
        host: str,
        port: int,
        events_per_second: float = 1000.0,
        rule_rows: Optional[List[Dict]] = None,
        rule_period_s: float = 5.0,
        seed: Optional[int] = None,
        batch_per_send: int = 500,
    ):
        self.schema = schema
        self.addr = (host, port)
        self.rate = events_per_second
        self.rule_rows = rule_rows or []
        self.rule_period_s = rule_period_s
        self.batch_per_send = batch_per_send
        self.gen = DataGenerator(schema, seed)
        self.events_sent = 0
        self.rule_events_sent = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sock = None

    # -- generation -------------------------------------------------------
    @staticmethod
    def _deep_merge(base: dict, overlay: dict) -> dict:
        """Overlay rule fields without clobbering sibling struct fields;
        dotted keys ("a.b") address nested fields directly."""
        out = dict(base)
        for k, v in overlay.items():
            if "." in k:
                head, rest = k.split(".", 1)
                out[head] = SimulatedDataService._deep_merge(
                    out.get(head) or {}, {rest: v}
                )
            elif isinstance(v, dict) and isinstance(out.get(k), dict):
                out[k] = SimulatedDataService._deep_merge(out[k], v)
            else:
                out[k] = v
        return out

    def make_batch(self, n: int, now_ms: int, with_rules: bool) -> List[dict]:
        rows = self.gen.random_rows(n, now_ms=now_ms)
        if with_rules and self.rule_rows:
            # overlay each rule template on a generated row so required
            # fields stay schema-complete (GenerateRulesData analog)
            for i, template in enumerate(self.rule_rows):
                rows[i % len(rows)] = self._deep_merge(
                    rows[i % len(rows)], template
                )
            self.rule_events_sent += len(self.rule_rows)
        return rows

    # -- send loop --------------------------------------------------------
    def _connect(self):
        return socket.create_connection(self.addr, timeout=10)

    def _send(self, rows: List[dict]) -> None:
        payload = b"".join(
            json.dumps(r, default=str).encode() + b"\n" for r in rows
        )
        try:
            if self._sock is None:
                self._sock = self._connect()
            self._sock.sendall(payload)
        except OSError:
            try:
                if self._sock is not None:
                    self._sock.close()
                self._sock = self._connect()
                self._sock.sendall(payload)
            except OSError as e:
                self._sock = None
                logger.warning("simulated data send failed: %s", e)
                return
        self.events_sent += len(rows)

    def run(self, duration_s: Optional[float] = None) -> None:
        """Paced send loop at the target rate; rule rows every period."""
        start = time.time()
        last_rule = 0.0
        while not self._stop.is_set():
            t0 = time.time()
            if duration_s is not None and t0 - start >= duration_s:
                break
            with_rules = (t0 - last_rule) >= self.rule_period_s
            if with_rules:
                last_rule = t0
            n = max(1, min(self.batch_per_send, int(self.rate)))
            self._send(self.make_batch(n, int(t0 * 1000), with_rules))
            # pace to the rate: n events should take n/rate seconds
            sleep = n / self.rate - (time.time() - t0)
            if sleep > 0:
                self._stop.wait(sleep)

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


def main(argv=None):
    """CLI: schema=<file> host=127.0.0.1 port=N rate=1000 [rules=<file>]"""
    import sys

    logging.basicConfig(level=logging.INFO)
    args = dict(
        a.split("=", 1) for a in (argv or sys.argv[1:]) if "=" in a
    )
    with open(args["schema"], "r", encoding="utf-8") as f:
        schema = Schema.from_spark_json(f.read())
    rule_rows = []
    if "rules" in args:
        with open(args["rules"], "r", encoding="utf-8") as f:
            rule_rows = [json.loads(x) for x in f.read().splitlines() if x.strip()]
    svc = SimulatedDataService(
        schema,
        args.get("host", "127.0.0.1"),
        int(args["port"]),
        events_per_second=float(args.get("rate", "1000")),
        rule_rows=rule_rows,
    )
    logger.info("simulated data -> %s:%s at %s ev/s", *svc.addr, svc.rate)
    try:
        svc.run(float(args["duration"]) if "duration" in args else None)
    except KeyboardInterrupt:
        svc.stop()


if __name__ == "__main__":
    main()
