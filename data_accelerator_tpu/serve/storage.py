"""Design-time and runtime config storage.

reference: DataX.Config/Storage/{IDesignTimeConfigStorage,
IRuntimeConfigStorage}.cs with the CosmosDB implementation for flow
documents and blob storage for runtime files; the local ("one-box")
implementations are DataX.Config.Local/{LocalDesignTimeStorage,
LocalRuntimeTimeStorage}.cs. Here the local filesystem is the primary
backend (TPU VMs mount shared storage); the interfaces keep the same
split so an object-store backend can slot in.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from typing import Dict, List, Optional


class DesignTimeStorage:
    """Flow documents keyed by flow name."""

    def get_by_name(self, name: str) -> Optional[dict]:
        raise NotImplementedError

    def get_all(self) -> List[dict]:
        raise NotImplementedError

    def save(self, doc: dict) -> dict:
        raise NotImplementedError

    def delete(self, name: str) -> bool:
        raise NotImplementedError


class LocalDesignTimeStorage(DesignTimeStorage):
    """One JSON file per flow under ``root/`` (diskdb analog)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, name: str) -> str:
        safe = "".join(c for c in name if c.isalnum() or c in "-_.")
        return os.path.join(self.root, f"{safe}.json")

    def get_by_name(self, name: str) -> Optional[dict]:
        p = self._path(name)
        if not os.path.exists(p):
            return None
        with open(p, "r", encoding="utf-8") as f:
            return json.load(f)

    def get_all(self) -> List[dict]:
        out = []
        for fn in sorted(os.listdir(self.root)):
            if fn.endswith(".json"):
                with open(os.path.join(self.root, fn), encoding="utf-8") as f:
                    out.append(json.load(f))
        return out

    def save(self, doc: dict) -> dict:
        name = doc.get("name")
        if not name:
            raise ValueError("flow document requires a 'name'")
        with self._lock:
            tmp = self._path(name) + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, self._path(name))
        return doc

    def delete(self, name: str) -> bool:
        p = self._path(name)
        if os.path.exists(p):
            os.remove(p)
            return True
        return False


class RuntimeStorage:
    """Generated runtime artifacts (conf, transform, projection, schema)."""

    def save_file(self, path: str, content: str) -> str:
        raise NotImplementedError

    def read_file(self, path: str) -> str:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def list_files(self, prefix: str) -> List[str]:
        """Relative paths of stored files under ``prefix``."""
        raise NotImplementedError

    def stored_path(self, path: str) -> str:
        """The reference a generated conf should carry for a stored
        artifact — a local absolute path here, an objstore:// URL for
        the object backend (workers resolve it via utils/fs.read_text,
        the HadoopClient-chokepoint role)."""
        raise NotImplementedError

    def delete_all(self, prefix: str) -> None:
        raise NotImplementedError


class LocalRuntimeStorage(RuntimeStorage):
    """Runtime files under a root dir; atomic temp+rename writes
    (reference: HadoopClient.scala:391-441 write semantics)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def resolve(self, path: str) -> str:
        return path if os.path.isabs(path) else os.path.join(self.root, path)

    def stored_path(self, path: str) -> str:
        return self.resolve(path)

    def save_file(self, path: str, content: str) -> str:
        full = self.resolve(path)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        tmp = full + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(content)
        os.replace(tmp, full)
        return full

    def read_file(self, path: str) -> str:
        with open(self.resolve(path), encoding="utf-8") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(self.resolve(path))

    def list_files(self, prefix: str) -> List[str]:
        base = self.resolve(prefix)
        if os.path.isfile(base):
            return [prefix]
        out: List[str] = []
        for dirpath, _dirs, files in os.walk(base):
            for fn in files:
                if fn.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                out.append(rel.replace(os.sep, "/"))
        return sorted(out)

    def delete_all(self, prefix: str) -> None:
        full = os.path.realpath(self.resolve(prefix))
        root = os.path.realpath(self.root)
        # recursive delete only ever inside the runtime root — a flow
        # name is caller-supplied and must not reach rmtree unconfined
        if not (full == root or full.startswith(root + os.sep)):
            raise ValueError(f"refusing to delete outside runtime root: {prefix}")
        if os.path.isdir(full):
            shutil.rmtree(full, ignore_errors=True)
        elif os.path.exists(full):
            os.remove(full)


class ObjectDesignTimeStorage(DesignTimeStorage):
    """Flow documents in a shared object store — the CosmosDB-role
    backend (reference: DataX.Config.Storage CosmosDB impl of
    IDesignTimeConfigStorage) so every control-plane replica sees the
    same designs. Keys: ``design/<name>.json``."""

    PREFIX = "design/"

    def __init__(self, client):
        from .objectstore import ObjectStoreClient  # noqa: F401 — type

        self.client = client

    def _key(self, name: str) -> str:
        safe = "".join(c for c in name if c.isalnum() or c in "-_.")
        return f"{self.PREFIX}{safe}.json"

    def get_by_name(self, name: str) -> Optional[dict]:
        data = self.client.get(self._key(name))
        return json.loads(data.decode()) if data is not None else None

    def get_all(self) -> List[dict]:
        out = []
        for key in self.client.list(self.PREFIX):
            data = self.client.get(key)
            if data is not None:
                out.append(json.loads(data.decode()))
        return out

    def save(self, doc: dict) -> dict:
        name = doc.get("name")
        if not name:
            raise ValueError("flow document requires a 'name'")
        self.client.put(self._key(name), json.dumps(doc, indent=1).encode())
        return doc

    def delete(self, name: str) -> bool:
        return self.client.delete(self._key(name))


class ObjectRuntimeStorage(RuntimeStorage):
    """Runtime artifacts in the shared object store — the blob-storage
    role (reference: IRuntimeConfigStorage blob impl), so a job
    submitted to a cluster host reads the exact configs the control
    plane generated. ``save_file`` returns an ``objstore://`` URL the
    engine resolves at startup (core/confmanager.py); local scratch
    (``resolve``) stays on disk for logs."""

    PREFIX = "runtime/"

    def __init__(self, client, scratch_dir: Optional[str] = None):
        self.client = client
        self.scratch = scratch_dir or os.path.join(
            os.path.expanduser("~"), ".dxtpu-scratch"
        )

    def _key(self, path: str) -> str:
        return self.PREFIX + path.lstrip("/")

    def resolve(self, path: str) -> str:
        """Local scratch path (logs etc. — host-local by design)."""
        if os.path.isabs(path):
            return path
        full = os.path.join(self.scratch, path)
        os.makedirs(os.path.dirname(full) or full, exist_ok=True)
        return full

    def save_file(self, path: str, content: str) -> str:
        key = self._key(path)
        self.client.put(key, content.encode())
        return self.client.url_for(key)

    def read_file(self, path: str) -> str:
        data = self.client.get(self._key(path))
        if data is None:
            raise FileNotFoundError(path)
        return data.decode()

    def exists(self, path: str) -> bool:
        # membership via the key listing — no object-body download
        key = self._key(path)
        return key in self.client.list(key)

    def list_files(self, prefix: str) -> List[str]:
        # directory semantics like the local backend: an exact-key file,
        # plus keys under the '/'-terminated prefix (a bare string
        # prefix would also match sibling flows sharing the spelling)
        n = len(self.PREFIX)
        key = self._key(prefix)
        out = []
        if prefix and key in self.client.list(key):
            out.append(prefix)
        term = key.rstrip("/") + "/" if prefix else self.PREFIX
        out.extend(k[n:] for k in self.client.list(term))
        return sorted(out)

    def stored_path(self, path: str) -> str:
        return self.client.url_for(self._key(path))

    def delete_all(self, prefix: str) -> None:
        # exact file, then the '/'-terminated subtree — never a bare
        # string prefix (deleting flow "iot" must not touch "iot2")
        key = self._key(prefix)
        self.client.delete(key)
        self.client.delete_prefix(key.rstrip("/") + "/")


class JobRegistry:
    """Job records (name -> record dict), stored alongside runtime configs.

    reference: DataX.Config SparkJobData/SparkJobConfig docs in the
    design-time store, upserted by S800_DeploySparkJob.cs:23-60.
    """

    def __init__(self, storage: RuntimeStorage):
        self.storage = storage
        self._lock = threading.Lock()

    def _path(self, name: str) -> str:
        return os.path.join("jobs", f"{name}.json")

    def upsert(self, record: dict) -> dict:
        name = record["name"]
        with self._lock:
            existing = self.get(name) or {}
            existing.update(record)
            self.storage.save_file(self._path(name), json.dumps(existing, indent=1))
        return existing

    def get(self, name: str) -> Optional[dict]:
        try:
            return json.loads(self.storage.read_file(self._path(name)))
        except FileNotFoundError:
            return None

    def get_all(self) -> List[dict]:
        out = []
        for rel in self.storage.list_files("jobs"):
            if rel.endswith(".json"):
                out.append(json.loads(self.storage.read_file(rel)))
        return out

    def delete(self, name: str) -> None:
        self.storage.delete_all(self._path(name))
