"""Design-time and runtime config storage.

reference: DataX.Config/Storage/{IDesignTimeConfigStorage,
IRuntimeConfigStorage}.cs with the CosmosDB implementation for flow
documents and blob storage for runtime files; the local ("one-box")
implementations are DataX.Config.Local/{LocalDesignTimeStorage,
LocalRuntimeTimeStorage}.cs. Here the local filesystem is the primary
backend (TPU VMs mount shared storage); the interfaces keep the same
split so an object-store backend can slot in.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from typing import Dict, List, Optional


class DesignTimeStorage:
    """Flow documents keyed by flow name."""

    def get_by_name(self, name: str) -> Optional[dict]:
        raise NotImplementedError

    def get_all(self) -> List[dict]:
        raise NotImplementedError

    def save(self, doc: dict) -> dict:
        raise NotImplementedError

    def delete(self, name: str) -> bool:
        raise NotImplementedError


class LocalDesignTimeStorage(DesignTimeStorage):
    """One JSON file per flow under ``root/`` (diskdb analog)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, name: str) -> str:
        safe = "".join(c for c in name if c.isalnum() or c in "-_.")
        return os.path.join(self.root, f"{safe}.json")

    def get_by_name(self, name: str) -> Optional[dict]:
        p = self._path(name)
        if not os.path.exists(p):
            return None
        with open(p, "r", encoding="utf-8") as f:
            return json.load(f)

    def get_all(self) -> List[dict]:
        out = []
        for fn in sorted(os.listdir(self.root)):
            if fn.endswith(".json"):
                with open(os.path.join(self.root, fn), encoding="utf-8") as f:
                    out.append(json.load(f))
        return out

    def save(self, doc: dict) -> dict:
        name = doc.get("name")
        if not name:
            raise ValueError("flow document requires a 'name'")
        with self._lock:
            tmp = self._path(name) + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, self._path(name))
        return doc

    def delete(self, name: str) -> bool:
        p = self._path(name)
        if os.path.exists(p):
            os.remove(p)
            return True
        return False


class RuntimeStorage:
    """Generated runtime artifacts (conf, transform, projection, schema)."""

    def save_file(self, path: str, content: str) -> str:
        raise NotImplementedError

    def read_file(self, path: str) -> str:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def delete_all(self, prefix: str) -> None:
        raise NotImplementedError


class LocalRuntimeStorage(RuntimeStorage):
    """Runtime files under a root dir; atomic temp+rename writes
    (reference: HadoopClient.scala:391-441 write semantics)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def resolve(self, path: str) -> str:
        return path if os.path.isabs(path) else os.path.join(self.root, path)

    def save_file(self, path: str, content: str) -> str:
        full = self.resolve(path)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        tmp = full + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(content)
        os.replace(tmp, full)
        return full

    def read_file(self, path: str) -> str:
        with open(self.resolve(path), encoding="utf-8") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(self.resolve(path))

    def delete_all(self, prefix: str) -> None:
        full = os.path.realpath(self.resolve(prefix))
        root = os.path.realpath(self.root)
        # recursive delete only ever inside the runtime root — a flow
        # name is caller-supplied and must not reach rmtree unconfined
        if not (full == root or full.startswith(root + os.sep)):
            raise ValueError(f"refusing to delete outside runtime root: {prefix}")
        if os.path.isdir(full):
            shutil.rmtree(full, ignore_errors=True)
        elif os.path.exists(full):
            os.remove(full)


class JobRegistry:
    """Job records (name -> record dict), stored alongside runtime configs.

    reference: DataX.Config SparkJobData/SparkJobConfig docs in the
    design-time store, upserted by S800_DeploySparkJob.cs:23-60.
    """

    def __init__(self, storage: LocalRuntimeStorage):
        self.storage = storage
        self._lock = threading.Lock()

    def _path(self, name: str) -> str:
        return os.path.join("jobs", f"{name}.json")

    def upsert(self, record: dict) -> dict:
        name = record["name"]
        with self._lock:
            existing = self.get(name) or {}
            existing.update(record)
            self.storage.save_file(self._path(name), json.dumps(existing, indent=1))
        return existing

    def get(self, name: str) -> Optional[dict]:
        if not self.storage.exists(self._path(name)):
            return None
        return json.loads(self.storage.read_file(self._path(name)))

    def get_all(self) -> List[dict]:
        jobs_dir = self.storage.resolve("jobs")
        if not os.path.isdir(jobs_dir):
            return []
        out = []
        for fn in sorted(os.listdir(jobs_dir)):
            if fn.endswith(".json"):
                out.append(json.loads(self.storage.read_file(
                    os.path.join("jobs", fn))))
        return out

    def delete(self, name: str) -> None:
        p = self.storage.resolve(self._path(name))
        if os.path.exists(p):
            os.remove(p)
