"""LiveQuery: interactive query kernels over sampled live data.

reference: DataX.Flow/DataX.Flow.InteractiveQuery —
``InteractiveQueryManager`` creates a remote Jupyter kernel on the Spark
cluster (HDInsightKernelService.cs:47-57), initializes it with the
flow's sampled input + normalization + UDFs/refdata
(KernelService.cs:67-130), executes the user's query and returns table
JSON capped at a max row count (KernelService.cs:451-540), and recycles
kernels via a tracked kernel list (KernelService.cs:135-190).

TPU-native shape: a kernel is an in-process object holding the sampled
batch; queries compile through the SAME FlowProcessor pipeline compiler
the production engine uses — the property the reference gets by running
the same Spark on both paths, we get by construction. Compiled
processors are cached per query text, so re-running an edited query
only recompiles the change.
"""

from __future__ import annotations

import json
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..constants import DatasetName
from ..core.config import SettingDictionary
from ..compile.transform_parser import TransformParser

_WINDOWED_TABLE_RE = re.compile(rf"\b{DatasetName.DataStreamProjection}_\w+\b")
# production TIMEWINDOW table naming: <projection>_<N><unit>
_WINDOW_NAME_RE = re.compile(
    rf"\b{DatasetName.DataStreamProjection}_(\d+)([A-Za-z]+)\b"
)
_DURATION_UNITS = {
    "second", "seconds", "minute", "minutes", "hour", "hours",
    "day", "days", "millisecond", "milliseconds",
}

DEFAULT_MAX_ROWS = 100
DEFAULT_KERNEL_TTL_S = 30 * 60
DEFAULT_MAX_KERNELS = 16


def _capacity_for(n: int) -> int:
    cap = 64
    while cap < n:
        cap *= 2
    return cap


@dataclass
class Kernel:
    """One interactive session's compiled state."""

    id: str
    flow_name: str
    schema_json: str
    normalization: str
    sample_rows: List[dict]
    udfs: Optional[dict] = None
    refdata_conf: Dict[str, str] = field(default_factory=dict)
    # sanitizer flags for UDF-bearing interactive runs: True arms both
    # jax.debug_nans and tracer-leak checking; a dict selects
    # individual process.debug.* flags ({"nans": "true"})
    debug: object = None
    # persistent-compile-cache conf keys (datax.job.process.compile.*)
    # merged into every query processor's conf: the kernel pool shares
    # one cache dir, so a re-created kernel (or a restarted control
    # plane) deserializes query compiles instead of re-tracing — the
    # warm-kernel-pool half of the AOT compile path
    compile_conf: Dict[str, str] = field(default_factory=dict)
    created_at: float = field(default_factory=time.time)
    last_used: float = field(default_factory=time.time)
    _processors: Dict[str, object] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def _conf(self, transform_text: str, windows: Dict[str, str],
              max_window_s: float) -> SettingDictionary:
        conf = {
            "datax.job.name": f"LiveQuery-{self.flow_name}",
            "datax.job.input.default.inputtype": "local",
            "datax.job.input.default.blobschemafile": self.schema_json,
            "datax.job.process.transform": transform_text,
            "datax.job.process.projection": self.normalization,
        }
        if windows:
            conf.update(windows)
            conf["datax.job.process.timestampcolumn"] = self._timestamp_column()
            conf["datax.job.process.watermark"] = "0 second"
            # the kernel runs ONE batch; sizing the interval to the max
            # window keeps the ring at 2 slots instead of window/1s
            conf["datax.job.input.default.streaming.intervalinseconds"] = str(
                max(1, int(max_window_s))
            )
        conf.update(self.refdata_conf)
        conf.update(self.compile_conf)
        if self.debug:
            # process.debug conf block (runtime/processor.py): the
            # kernel's one-batch runs are exactly the "test job" the
            # sanitizers exist for — impure/NaN-producing UDFs fail
            # loudly here instead of shipping
            flags = (
                {"nans": "true", "tracerleaks": "true"}
                if self.debug is True
                else {k: str(v).lower() for k, v in dict(self.debug).items()}
            )
            for k, v in flags.items():
                conf[f"datax.job.process.debug.{k}"] = v
        return SettingDictionary(conf)

    def _timestamp_column(self) -> Optional[str]:
        """The time axis windows evict against: the schema's first
        TIMESTAMP column, else the alias a current_timestamp()
        normalization line introduces. Cached — called per execute."""
        if not hasattr(self, "_ts_col"):
            from ..core.schema import ColType, Schema

            col = None
            try:
                schema = Schema.from_spark_json(self.schema_json)
                for c in schema.columns:
                    if c.ctype == ColType.TIMESTAMP:
                        col = c.name
                        break
            except (ValueError, KeyError):
                pass
            if col is None:
                m = re.search(
                    r"current_timestamp\(\)\s+AS\s+(\w+)",
                    self.normalization, re.I,
                )
                col = m.group(1) if m else None
            self._ts_col = col
        return self._ts_col

    def _window_confs(self, query: str):
        """TIMEWINDOW conf entries for every windowed table the query
        names, parsed from the production ``<projection>_<N><unit>``
        naming — so the kernel runs the SAME ring-buffer/watermark
        window machinery as the production engine
        (reference's same-engine promise, KernelService.cs:104-130),
        with the sample's own time axis deciding what's in-window."""
        if self._timestamp_column() is None:
            return {}, 0.0
        confs: Dict[str, str] = {}
        max_s = 0.0
        for n, unit in set(_WINDOW_NAME_RE.findall(query)):
            if unit.lower() not in _DURATION_UNITS:
                continue
            name = f"{DatasetName.DataStreamProjection}_{n}{unit}"
            confs[
                f"datax.job.process.timewindow.{name}.windowduration"
            ] = f"{n} {unit}"
            scale = {
                "millisecond": 0.001, "second": 1, "minute": 60,
                "hour": 3600, "day": 86400,
            }[unit.lower().rstrip("s")]
            max_s = max(max_s, int(n) * scale)
        return confs, max_s

    def _rewrite_windowed(self, query: str, windows: Dict[str, str]) -> str:
        """Windowed tables the production naming does NOT cover (no
        parseable duration) alias to the full sample as a fallback;
        properly-named ones run the real TIMEWINDOW machinery via
        ``_window_confs``."""
        real = {
            key.split(".timewindow.", 1)[1].rsplit(".", 1)[0]
            for key in windows
        }
        return _WINDOWED_TABLE_RE.sub(
            lambda m: m.group(0)
            if m.group(0) in real
            else DatasetName.DataStreamProjection,
            query,
        )

    def _sample_base_ms(self) -> int:
        """The sample's own epoch-ms origin: the max value of the
        schema's TIMESTAMP columns across sampled rows — string and
        nested timestamps included (falls back to now for
        timestamp-less samples)."""
        from ..core.batch import _dig, parse_timestamp_ms
        from ..core.schema import ColType, Schema

        try:
            schema = Schema.from_spark_json(self.schema_json)
        except (ValueError, KeyError):
            return int(time.time() * 1000)
        ts_cols = [c.name for c in schema.columns if c.ctype == ColType.TIMESTAMP]
        best = 0
        for r in self.sample_rows:
            for cname in ts_cols:
                v = _dig(r, cname)
                if isinstance(v, str):
                    v = parse_timestamp_ms(v)
                if isinstance(v, (int, float)) and v > 0:
                    best = max(best, int(v))
        return best or int(time.time() * 1000)

    def execute(self, query: str, max_rows: int = DEFAULT_MAX_ROWS) -> dict:
        """Compile + run the query against the sampled batch; returns
        {"headers": [...], "result": [rows]} like the reference's
        ConvertToJson (KernelService.cs:700)."""
        from ..runtime.processor import FlowProcessor

        self.last_used = time.time()
        windows, max_window_s = self._window_confs(query)
        text = self._rewrite_windowed(query.strip(), windows)
        if not text:
            return {"headers": [], "result": []}

        # target dataset: last named assignment in the script
        parsed = TransformParser.parse(text.splitlines())
        names = [c.name for c in parsed.commands if c.name]
        if not names:
            # bare SELECT: wrap into an assignment
            text = f"__livequery__ = {text}"
            names = ["__livequery__"]
        target = names[-1]

        with self._lock:
            proc = self._processors.get(text)
            if proc is None:
                proc = FlowProcessor(
                    self._conf(text, windows, max_window_s),
                    batch_capacity=_capacity_for(len(self.sample_rows)),
                    output_datasets=[target],
                    udfs=self.udfs,
                )
                self._processors[text] = proc
            else:
                # a cached processor holds ring/state from its last run;
                # kernel executes are idempotent, so start clean
                proc.reset_state()

        # anchor the batch at the SAMPLE's time base, not the wall
        # clock: sampled blobs may be hours/days old and relative int32
        # times must stay small (production gets this for free — live
        # batches are near now)
        base_ms = self._sample_base_ms()
        raw = proc.encode_rows(self.sample_rows, (base_ms // 1000) * 1000)
        datasets, _metrics = proc.process_batch(raw, batch_time_ms=base_ms)
        rows = datasets.get(target, [])[:max_rows]
        headers = list(rows[0].keys()) if rows else []
        return {"headers": headers, "result": rows, "table": target}


class KernelService:
    """Kernel registry with TTL GC (KernelService.cs:135-190 analog).

    The registry itself is the serving plane's ``SessionManager``
    (``lq/session.py``) — kernels live as sessions under the legacy
    tenant, so BOTH interactive surfaces (these designer kernels and
    the multi-tenant ``lq/`` session service) share one registry, one
    TTL clock and one reap pass. That also fixes the old leak: GC used
    to run only inside ``create_kernel``, so REST-created kernels whose
    designer stopped creating new ones were never reaped; the shared
    manager reaps on EVERY access path (create, get, execute, list)."""

    def __init__(
        self,
        runtime_storage=None,
        ttl_s: float = DEFAULT_KERNEL_TTL_S,
        max_kernels: int = DEFAULT_MAX_KERNELS,
        compile_conf: Optional[Dict[str, str]] = None,
        session_manager=None,
    ):
        from ..lq.session import LEGACY_TENANT, SessionManager

        self.runtime = runtime_storage
        self.max_kernels = max_kernels
        # shared persistent-compile-cache conf applied to every kernel
        # (see Kernel.compile_conf)
        self.compile_conf = dict(compile_conf or {})
        self._tenant = LEGACY_TENANT
        self.sessions = session_manager or SessionManager(ttl_s=ttl_s)
        self.ttl_s = self.sessions.ttl_s

    # -- lifecycle -------------------------------------------------------
    def create_kernel(
        self,
        flow_name: str,
        schema_json: str,
        normalization: str = "Raw.*",
        sample_rows: Optional[List[dict]] = None,
        udfs: Optional[dict] = None,
        refdata_conf: Optional[Dict[str, str]] = None,
        debug: object = None,
    ) -> str:
        """Create + initialize a kernel; returns kernel id.

        Sample rows default to the flow's persisted sample blob
        (written by SchemaInferenceManager). ``debug`` arms the
        ``process.debug`` sanitizers (jax.debug_nans + tracer-leak
        checking) for this kernel's runs."""
        if sample_rows is None:
            sample_rows = self._load_sample(flow_name)
        if not isinstance(schema_json, str):
            schema_json = json.dumps(schema_json)
        kernel = Kernel(
            id="",
            flow_name=flow_name,
            schema_json=schema_json,
            normalization=normalization,
            sample_rows=sample_rows or [],
            udfs=udfs,
            refdata_conf=refdata_conf or {},
            debug=debug,
            compile_conf=dict(self.compile_conf),
        )
        # legacy policy: evict the oldest-idle kernel when this
        # surface's cap is reached (the designer's recycle-oldest
        # behavior), instead of the serving plane's 429 rejection
        session = self.sessions.create(
            tenant=self._tenant,
            flow_name=flow_name,
            payload=kernel,
            evict_on_full=True,
            cap=self.max_kernels,
        )
        kernel.id = session.id
        return session.id

    def has_sample(self, flow_name: str) -> bool:
        """True when a persisted sample blob exists for the flow."""
        return (
            self.runtime is not None
            and bool(flow_name)
            and self.runtime.exists(self._sample_rel(flow_name))
        )

    @staticmethod
    def _sample_rel(flow_name: str) -> str:
        return f"{flow_name}/samples/sample.json"

    def _load_sample(self, flow_name: str) -> List[dict]:
        if not self.has_sample(flow_name):
            return []
        return [
            json.loads(ln)
            for ln in self.runtime.read_file(self._sample_rel(flow_name)).splitlines()
            if ln.strip()
        ]

    def get(self, kernel_id: str) -> Kernel:
        # the shared manager reaps expired sessions on every get — a
        # REST-created kernel left idle past its TTL is recycled here,
        # not only when the next create happens to run
        try:
            session = self.sessions.get(kernel_id)
        except KeyError:
            raise KeyError(f"kernel '{kernel_id}' not found (recycled?)")
        if session.tenant != self._tenant or session.payload is None:
            raise KeyError(f"kernel '{kernel_id}' not found (recycled?)")
        return session.payload

    def execute(
        self, kernel_id: str, query: str, max_rows: int = DEFAULT_MAX_ROWS
    ) -> dict:
        return self.get(kernel_id).execute(query, max_rows)

    def delete_kernel(self, kernel_id: str) -> bool:
        return self.sessions.close(kernel_id)

    def delete_kernels(self, flow_name: Optional[str] = None) -> int:
        """Recycle all kernels (optionally per flow)."""
        return self.sessions.close_where(
            flow_name=flow_name, tenant=self._tenant
        )

    def list_kernels(self) -> List[dict]:
        return [
            {
                "id": s.id,
                "flow": s.flow_name,
                "createdAt": s.created_at,
                "lastUsed": s.last_used,
                "sampleRows": len(s.payload.sample_rows)
                if s.payload is not None else 0,
            }
            for s in self.sessions.list(tenant=self._tenant)
        ]
