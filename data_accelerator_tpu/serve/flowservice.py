"""Flow lifecycle public service: save, generate, start/stop/restart.

reference: DataX.Config/PublicService/{FlowOperation,JobOperation}.cs —
``SaveFlowConfig`` (FlowOperation.cs:112) builds/merges the flow doc and
upserts design-time storage; ``GenerateConfigs`` runs the S100–S900
chain; ``StartJobsForFlow``/``StopJobsForFlow``/``RestartJobsForFlow``
(FlowOperation.cs:158+) fan out to SparkJobOperation per job name;
``ScheduleBatch`` (FlowOperation.cs:88) registers batch rounds. The
DeleteHelper cascade (DataX.Flow.DeleteHelper) is ``delete_flow``.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from .flowbuilder import FlowConfigBuilder
from .generation import GenerationResult, RuntimeConfigGeneration
from .jobs import (
    FleetAdmissionGate,
    JobOperation,
    JobState,
    LocalJobClient,
    TpuJobClient,
)
from .scheduler import PlacementReplanner
from .storage import DesignTimeStorage, JobRegistry, LocalRuntimeStorage

logger = logging.getLogger(__name__)


class FlowOperation:
    """The control plane's front door (one per service process)."""

    def __init__(
        self,
        design_storage: DesignTimeStorage,
        runtime_storage: LocalRuntimeStorage,
        job_client: Optional[TpuJobClient] = None,
        env_tokens: Optional[dict] = None,
        fleet_spec=None,
        fleet_admission: bool = True,
    ):
        self.design = design_storage
        self.runtime = runtime_storage
        self.builder = FlowConfigBuilder()
        self.generation = RuntimeConfigGeneration(
            design_storage, runtime_storage, env_tokens=env_tokens
        )
        self.registry: JobRegistry = self.generation.jobs
        # fleet placement as an admission gate: every job submit is
        # checked against the DX4xx analyzer before a process spawns
        # (``fleet_admission=False`` runs the reference's blind-deploy
        # behavior; ``fleet_spec`` is an ``analysis.FleetSpec``)
        self.fleet_gate: Optional[FleetAdmissionGate] = None
        self.placement: Optional[PlacementReplanner] = None
        if fleet_admission:
            self.fleet_gate = FleetAdmissionGate(
                design_storage, self.registry, spec=fleet_spec
            )
            self.placement = PlacementReplanner(self.fleet_gate)
        self.jobs = JobOperation(
            self.registry,
            job_client or LocalJobClient(log_dir=runtime_storage.resolve("logs")),
            admission_gate=self.fleet_gate,
            replanner=self.placement,
        )

    # -- design-time -----------------------------------------------------
    def save_flow(self, gui: dict) -> dict:
        """reference: FlowOperation.SaveFlowConfig (FlowOperation.cs:112)."""
        name = gui.get("name")
        existing = self.design.get_by_name(name) if name else None
        doc = self.builder.build(gui, existing=existing)
        return self.design.save(doc)

    def get_flow(self, name: str) -> Optional[dict]:
        return self.design.get_by_name(name)

    def get_all_flows(self) -> List[dict]:
        return self.design.get_all()

    def validate_flow(self, flow: dict):
        """Static analysis over a flow config (gui JSON or full doc) —
        the same implementation the CLI runs, so the ``validate``
        endpoint and ``python -m data_accelerator_tpu.analysis`` can
        never drift. Returns an ``analysis.AnalysisReport``. (Imported
        lazily: analysis reuses serve.flowbuilder for rule expansion.)"""
        from ..analysis import analyze_flow

        return analyze_flow(flow)

    def validate_flow_device(self, flow: dict, chips=None):
        """The device tier of ``flow/validate`` (``device: true``):
        abstract interpretation of the compiled plan — per-stage
        HBM/FLOP/ICI cost report plus the DX2xx capacity lints. Same
        implementation as the CLI's ``--device``; no device executes."""
        from ..analysis import analyze_flow_device

        return analyze_flow_device(flow, chips=chips)

    def validate_flow_udfs(self, flow: dict):
        """The UDF tier of ``flow/validate`` (``udfs: true``): every
        declared UDF/UDAF resolves through the production loader and
        its device functions are abstract-interpreted under the taint
        lattice — the DX3xx tracing-safety/purity/determinism lints.
        Same implementation as the CLI's ``--udfs``."""
        from ..analysis import analyze_flow_udfs

        return analyze_flow_udfs(flow)

    def validate_flow_compile(self, flow: dict, manifest: Optional[dict] = None):
        """The compile tier of ``flow/validate`` (``compile: true``):
        every jit entry point the flow will ever dispatch is enumerated
        and lowered over ``jax.eval_shape`` avals — the DX6xx
        finiteness/stability lints plus the AOT compile manifest.
        ``manifest`` (body ``compileManifest``) additionally checks a
        previously emitted manifest for drift (DX602/DX603). Same
        implementation as the CLI's ``--compile``; no device executes."""
        from ..analysis import analyze_flow_compile

        return analyze_flow_compile(flow, manifest=manifest)

    def validate_flow_mesh(self, flow: dict, chips=None):
        """The mesh tier of ``flow/validate`` (``mesh: true``): the
        flow's static SPMD partition plan — per-stage shard axis,
        reshard edges, closed-form collective bytes — with the DX7xx
        lints, cross-checked against a real ``Mesh`` lowering when the
        control plane has >= 2 devices (else the plan is emitted
        unvalidated with DX791). Same implementation as the CLI's
        ``--mesh``; no device executes."""
        from ..analysis import analyze_flow_mesh

        return analyze_flow_mesh(flow, chips=chips)

    def validate_flow_race(self, flow: dict):
        """The race tier of ``flow/validate`` (``race: true``): the
        DX8xx buffer-lifetime/concurrency gate over the ENGINE modules
        the flow would deploy onto (``runtime/``, ``lq/``, ``pilot/``)
        — a provenance-lattice abstract interpretation of the runtime's
        own source, cached per engine-source state. Same implementation
        as the CLI's ``--race``; nothing executes."""
        from ..analysis import analyze_flow_race

        return analyze_flow_race(flow)

    def validate_flow_protocol(self, flow: dict):
        """The protocol tier of ``flow/validate`` (``protocol: true``):
        the DX90x exactly-once delivery gate over the engine modules
        plus the rescale handoff (``serve/jobs.py``) — typed effect
        traces checked against the declared ordering spec, cached per
        engine-source state. Same implementation as the CLI's
        ``--protocol``; nothing executes."""
        from ..analysis import analyze_flow_protocol

        return analyze_flow_protocol(flow)

    def validate_flow_conf(self, flow: dict):
        """The conf tier of ``flow/validate`` (``conf: true``): the
        DX10xx configuration-lattice gate — engine conf read sites and
        generation-produced keys checked against the typed registry
        (``analysis/confspec.py``), plus type/bounds (DX1004) and
        incompatible-knob (DX1005) checks on THIS flow's effective
        conf. Cached per engine-source state. Same implementation as
        the CLI's ``--conf``; nothing executes."""
        from ..analysis import analyze_flow_conf

        return analyze_flow_conf(flow)

    def validate_flow_fleet(self, flow: dict, spec: Optional[dict] = None):
        """The fleet tier of ``flow/validate`` (``fleet: true``): the
        candidate flow is analyzed AS A SET with every currently
        registered flow against the fleet spec (body ``fleetSpec``
        overrides the default) — the DX4xx capacity/interference lints
        plus the placement plan. Same analyzer the CLI's ``--fleet``
        and the job-submission admission gate run."""
        from ..analysis import FleetSpec, analyze_fleet_flows

        gui = flow.get("gui") if isinstance(flow.get("gui"), dict) else flow
        name = (gui or {}).get("name")
        # a re-save of an existing flow competes with the OTHER flows,
        # not its own registered copy
        others = [
            d for d in self.design.get_all() if d.get("name") != name
        ]
        return analyze_fleet_flows(
            [flow] + others,
            spec=FleetSpec.from_dict(spec) if spec else None,
        )

    def generate_configs(self, flow_name: str) -> GenerationResult:
        doc = self.design.get_by_name(flow_name)
        if doc is not None:
            # deploy gate: a flow whose OUTPUT routes a dataset no
            # transform produces would generate and start a job that
            # produces nothing — fail here with the analyzer's
            # unbound-reference diagnostic instead. Fail-open: an
            # analyzer crash must not block generation (generation has
            # its own validation stages).
            try:
                report = self.validate_flow(doc)
            except Exception:  # noqa: BLE001
                logger.exception("flow validation failed for %s", flow_name)
            else:
                unbound = [d for d in report.errors if d.code == "DX003"]
                if unbound:
                    return GenerationResult(
                        flow_name, errors=[d.render() for d in unbound]
                    )
        return self.generation.generate(flow_name)

    # -- runtime ---------------------------------------------------------
    def _flow_job_names(self, flow_name: str) -> List[str]:
        doc = self.design.get_by_name(flow_name)
        if not doc:
            raise KeyError(f"flow '{flow_name}' not found")
        names = doc.get("jobNames") or []
        if not names:
            raise ValueError(
                f"flow '{flow_name}' has no generated jobs; run generateconfigs"
            )
        return names

    def start_jobs(self, flow_name: str, batches: Optional[int] = None) -> List[dict]:
        return [
            self.jobs.start_job_with_retries(n, batches=batches)
            for n in self._flow_job_names(flow_name)
        ]

    def stop_jobs(self, flow_name: str) -> List[dict]:
        return [
            self.jobs.stop_job_with_retries(n)
            for n in self._flow_job_names(flow_name)
        ]

    def restart_jobs(self, flow_name: str, batches: Optional[int] = None) -> List[dict]:
        return [
            self.jobs.restart_job(n, batches=batches)
            for n in self._flow_job_names(flow_name)
        ]

    def sync_jobs(self, flow_name: Optional[str] = None) -> List[dict]:
        if flow_name is None:
            return self.jobs.sync_all()
        return [self.jobs.sync_job_state(n) for n in self._flow_job_names(flow_name)]

    def schedule_batch(self, flow_name: str) -> List[dict]:
        """Trigger one batch round for a batch-mode flow
        (reference: FlowOperation.ScheduleBatch, FlowOperation.cs:88 —
        recurring scheduling is the TimedScheduler's job)."""
        res = self.generate_configs(flow_name)
        if not res.ok:
            raise RuntimeError(f"generateconfigs failed: {res.errors}")
        return self.start_jobs(flow_name)

    # -- delete cascade --------------------------------------------------
    def delete_flow(self, flow_name: str) -> bool:
        """Stop jobs, drop runtime artifacts + job records + flow doc
        (reference: DataX.Flow.DeleteHelper cascade)."""
        doc = self.design.get_by_name(flow_name)
        if doc is None:
            return False
        for job_name in doc.get("jobNames") or []:
            try:
                self.jobs.stop_job_with_retries(job_name)
            except Exception:  # noqa: BLE001 — best-effort stop during delete
                logger.warning("failed stopping job %s during delete", job_name)
            self.registry.delete(job_name)
        self.runtime.delete_all(flow_name)
        return self.design.delete(flow_name)
