"""Flow document construction from designer ("gui") JSON.

reference: DataX.Config/ConfigDataModel/FlowConfigBuilder + the default
flow template seeded into the config store
(DataX.Config.Local/Resources/*, DeploymentCloud/Deployment.Common/
CosmosDB/flowCommonTemplate.json) and
InternalService/RuleDefinitionGenerator.cs:31-32 (gui rules ->
rule-definition JSON consumed by CodegenRules).

A flow document is::

    {"name", "displayName", "gui": {...designer state...},
     "commonProcessor": {"template": {... _S_{token} placeholders ...},
                         "jobCommonTokens": {...}, "jobs": [...]},
     "metrics": {...}, "jobNames": [...]}

The template keeps the reference's shape and token names
(HomeAutomationLocal.json commonProcessor.template) so flow documents
written for the reference generate here unchanged; job tokens are
TPU-flavored (chips/mesh instead of executors/memory).
"""

from __future__ import annotations

import copy
import json
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Default flow template. Same placeholder vocabulary as the reference's
# commonProcessor.template; resolved by RuntimeConfigGeneration.
# ---------------------------------------------------------------------------
DEFAULT_TEMPLATE: Dict[str, Any] = {
    "name": "_S_{name}",
    "input": {
        "inputType": "_S_{inputType}",
        "eventhub": {
            "connectionString": "_S_{inputEventHubConnectionString}",
            "consumerGroup": "_S_{inputEventHubConsumerGroup}",
            "checkpointDir": "_S_{inputEventHubCheckpointDir}",
            "checkpointInterval": "_S_{inputEventHubCheckpointInterval}",
            "maxRate": "_S_{inputEventHubMaxRate}",
            "flushExistingCheckpoints": "_S_{inputEventHubFlushExistingCheckpoints}",
        },
        "streaming": {
            "checkpointDir": "_S_{inputStreamingCheckpointDir}",
            "intervalInSeconds": "_S_{inputStreamingIntervalInSeconds}",
        },
        "blobSchemaFile": "_S_{inputSchemaFilePath}",
        "referenceData": "_S_{inputReferenceData}",
    },
    "process": {
        "metric": {"httppost": "_S_{localMetricsHttpEndpoint}"},
        "timestampColumn": "_S_{processTimestampColumn}",
        "watermark": "_S_{processWatermark}",
        "jarUDAFs": "_S_{processJarUDAFs}",
        "jarUDFs": "_S_{processJarUDFs}",
        "azureFunctions": "_S_{processAzureFunctions}",
        "projections": "_S_{processProjections}",
        "timeWindows": "_S_{processTimeWindows}",
        "transform": "_S_{processTransforms}",
        "appendEventTags": {},
        "accumulationTables": "_S_{processStateTables}",
    },
    "outputs": "_S_{outputs}",
}

DEFAULT_JOB_COMMON_TOKENS: Dict[str, str] = {
    "jobName": "_S_{name}",
    "tpuJobName": "DataXTpu-${name}",
    "jobDriverLogLevel": "WARN",
    "jobNumChips": "_S_{guiJobNumChips}",
    "jobBatchCapacity": "_S_{guiJobBatchCapacity}",
    "jobPipelineDepth": "_S_{guiJobPipelineDepth}",
    "jobDecoderThreads": "_S_{guiJobDecoderThreads}",
    "jobObservabilityPort": "_S_{guiJobObservabilityPort}",
    "jobCompileJitCacheCap": "_S_{guiJobCompileJitCacheCap}",
    "processedSchemaPath": "_S_{processedSchemaPath}",
}

DEFAULT_COMMON_PROCESSOR: Dict[str, Any] = {
    "jobConfigFolder": "_S_{cpConfigFolderBase}/${name}",
    "template": DEFAULT_TEMPLATE,
    "jobCommonTokens": DEFAULT_JOB_COMMON_TOKENS,
    "jobs": [{"partitionJobNumber": "1"}],
}


def _deep_merge(base: Any, override: Any) -> Any:
    """override wins; dicts merge recursively (reference: template merge
    in FlowConfigBuilder / S200 defaults merge)."""
    if isinstance(base, dict) and isinstance(override, dict):
        out = dict(base)
        for k, v in override.items():
            out[k] = _deep_merge(base.get(k), v) if k in base else v
        return out
    return override if override is not None else base


class FlowConfigBuilder:
    """Build/refresh a flow document from designer gui JSON."""

    def build(self, gui: dict, existing: Optional[dict] = None) -> dict:
        name = gui.get("name") or (existing or {}).get("name")
        if not name:
            raise ValueError("gui.name is required")
        doc = copy.deepcopy(existing) if existing else {}
        doc["name"] = name
        doc["displayName"] = gui.get("displayName") or name
        doc.setdefault("icon", "/img/iot.png")
        doc["gui"] = gui
        doc["commonProcessor"] = _deep_merge(
            copy.deepcopy(DEFAULT_COMMON_PROCESSOR),
            doc.get("commonProcessor") or {},
        )
        return doc


# ---------------------------------------------------------------------------
# gui rules -> rule-definition JSON for the codegen engine
# ---------------------------------------------------------------------------

def _q(v) -> str:
    """SQL single-quoted literal with quote doubling — designer values
    like O'Brien must not break (or splice into) the generated SQL."""
    return "'" + str(v).replace("'", "''") + "'"


def _lk(v) -> str:
    """LIKE pattern body, quote-escaped (wildcards added by caller)."""
    return str(v).replace("'", "''")


# gui condition operator -> SQL fragment builder. The gui's no-code rule
# tree (datax-pipeline rule builder) emits these operator names.
_OPERATORS = {
    "equal": lambda f, v: f"{f} = {v}",
    "notEqual": lambda f, v: f"{f} != {v}",
    "greaterThan": lambda f, v: f"{f} > {v}",
    "lessThan": lambda f, v: f"{f} < {v}",
    "greaterThanOrEqual": lambda f, v: f"{f} >= {v}",
    "lessThanOrEqual": lambda f, v: f"{f} <= {v}",
    "stringEqual": lambda f, v: f"{f} = {_q(v)}",
    "stringNotEqual": lambda f, v: f"{f} != {_q(v)}",
    "contains": lambda f, v: f"{f} LIKE '%{_lk(v)}%'",
    "notContains": lambda f, v: f"{f} NOT LIKE '%{_lk(v)}%'",
    "startsWith": lambda f, v: f"{f} LIKE '{_lk(v)}%'",
    "endsWith": lambda f, v: f"{f} LIKE '%{_lk(v)}'",
    "isNull": lambda f, v: f"{f} IS NULL",
    "isNotNull": lambda f, v: f"{f} IS NOT NULL",
}


def _condition_sql(node: dict, aggregate_mode: bool) -> str:
    """gui conditions tree -> SQL boolean expression."""
    if not node:
        return ""
    if node.get("type") == "group":
        # keep (child, sql) pairs aligned so each child's conjunction
        # joins its own fragment even when siblings produce no SQL
        rendered = [
            (c, _condition_sql(c, aggregate_mode))
            for c in node.get("conditions") or []
        ]
        rendered = [(c, sql) for c, sql in rendered if sql]
        if not rendered:
            return ""
        joined = []
        for i, (child, sql) in enumerate(rendered):
            if i > 0:
                joined.append((child.get("conjunction") or "and").upper())
            joined.append(f"({sql})" if child.get("type") == "group" else sql)
        return " ".join(joined)
    field = node.get("field") or ""
    if aggregate_mode and node.get("aggregate"):
        field = f"{node['aggregate'].upper()}({field})"
    op = _OPERATORS.get(node.get("operator") or "equal", _OPERATORS["equal"])
    return op(field, node.get("value"))


def _collect_aggs(node: dict, out: List[str]) -> None:
    if not node:
        return
    if node.get("type") == "group":
        for c in node.get("conditions") or []:
            _collect_aggs(c, out)
        return
    if node.get("aggregate") and node.get("field"):
        agg = f"{node['aggregate'].upper()}({node['field']})"
        if agg not in out:
            out.append(agg)


class RuleDefinitionGenerator:
    """gui rules list -> rule-definition JSON string.

    reference: InternalService/RuleDefinitionGenerator.cs:31-32 — the
    gui rule's ``properties`` object *is* the definition; ``_S_``-prefixed
    designer property names map to the ``$``-prefixed keys the codegen
    rule parser reads (DataX.Flow.CodegenRules/Rule.cs:19-73). When the
    designer supplied a conditions tree but no precomputed condition,
    derive the SQL here.
    """

    def generate(self, gui_rules: List[dict], product_id: str = "") -> str:
        defs = []
        for r in gui_rules or []:
            props = dict(r.get("properties") or {})
            d: Dict[str, Any] = {}
            for k, v in props.items():
                if k.startswith("_S_"):
                    d["$" + k[len("_S_"):]] = v
                elif k.startswith("$") or k in ("schemaTableName", "conditions"):
                    d[k] = v
            d.setdefault("$ruleId", r.get("id") or "")
            if product_id and not d.get("$productId"):
                d["$productId"] = product_id
            rule_type = d.get("$ruleType") or "SimpleRule"
            aggregate_mode = rule_type.startswith("Aggregate")
            tree = props.get("conditions")
            if tree and not d.get("$condition"):
                d["$condition"] = _condition_sql(tree, aggregate_mode)
            if tree and aggregate_mode and not d.get("$aggs"):
                aggs: List[str] = []
                _collect_aggs(tree, aggs)
                d["$aggs"] = aggs
            # normalize key casing differences between designer and parser
            if "$tagName" in d and "$tagname" not in d:
                d["$tagname"] = d.pop("$tagName")
            if "$alertSinks" in d and "$alertsinks" not in d:
                d["$alertsinks"] = d.pop("$alertSinks")
            # a rule routed to alert sinks is an alert unless said otherwise
            # (the designer's Alert toggle maps here)
            if d.get("$alertsinks") and "$isAlert" not in d:
                d["$isAlert"] = True
            defs.append(d)
        return json.dumps(defs)
