"""Schema inference: sampled JSON events -> Spark-style schema JSON.

reference: DataX.Flow/DataX.Flow.SchemaInference —
``SchemaInferenceManager.GetInputSchema`` samples N seconds of live
events from the message bus ({Eventhub,Kafka,Blob}/*MessageBus.cs:43)
and ``Engine.GetSchema``/``SchemaGenerator`` merges the JSON shapes into
one schema document (Engine.cs:23-65) plus a sample-data blob consumed
by LiveQuery kernel init (KernelService.cs:104-130).

The schema format matches the engine's input contract
(core/schema.py parse of ``{"type":"struct","fields":[...]}``).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


# -- type lattice ------------------------------------------------------------
# merge order: conflicting scalars widen long->double->string
_WIDEN = {
    ("long", "double"): "double",
    ("double", "long"): "double",
}


def _json_type(value: Any) -> Tuple[str, Any]:
    """Returns (type-name or 'struct'/'array', nested payload)."""
    if isinstance(value, bool):
        return "boolean", None
    if isinstance(value, int):
        return "long", None
    if isinstance(value, float):
        return "double", None
    if isinstance(value, str):
        return "string", None
    if isinstance(value, dict):
        return "struct", value
    if isinstance(value, list):
        return "array", value
    return "null", None  # None -> type decided by other samples


# distinct scalar values tracked per field before the set is declared
# high-cardinality and dropped; surviving sets become ``allowedValues``
# metadata — the sampled-cardinality surface the device-plan analyzer's
# DX200/DX202 capacity lints and utils/datagen.py consume
_MAX_SAMPLED_VALUES = 64


@dataclass
class _FieldAcc:
    """Accumulated evidence for one field across samples."""

    type: str = "null"
    struct: Optional["_StructAcc"] = None
    element: Optional["_FieldAcc"] = None
    seen: int = 0
    nullable: bool = False
    values: set = field(default_factory=set)
    values_overflow: bool = False

    def observe(self, value: Any) -> None:
        self.seen += 1
        t, payload = _json_type(value)
        if t == "null":
            self.nullable = True
            return
        if t == "struct":
            if self.struct is None:
                self.struct = _StructAcc()
            self.struct.observe(payload)
            self.type = "struct" if self.type in ("null", "struct") else "string"
            return
        if t == "array":
            if self.element is None:
                self.element = _FieldAcc()
            for item in payload:
                self.element.observe(item)
            self.type = "array" if self.type in ("null", "array") else "string"
            return
        if t in ("string", "long", "boolean") and not self.values_overflow:
            self.values.add(value)
            if len(self.values) > _MAX_SAMPLED_VALUES:
                self.values_overflow = True
                self.values.clear()
        if self.type == "null":
            self.type = t
        elif self.type != t:
            self.type = _WIDEN.get((self.type, t), "string")

    def sampled_metadata(self) -> dict:
        """``allowedValues`` for a low-cardinality scalar field whose
        samples all share the final type (a widened/mixed field has no
        meaningful value set)."""
        if self.values_overflow or not self.values:
            return {}
        homogeneous = {
            "string": lambda v: isinstance(v, str),
            "boolean": lambda v: isinstance(v, bool),
            "long": lambda v: isinstance(v, int) and not isinstance(v, bool),
        }.get(self.type)
        if homogeneous is None or not all(map(homogeneous, self.values)):
            return {}
        return {"allowedValues": sorted(self.values)}

    def to_schema_type(self) -> Any:
        if self.type == "struct" and self.struct is not None:
            return self.struct.to_schema()
        if self.type == "array":
            elem = self.element.to_schema_type() if self.element else "string"
            return {
                "type": "array",
                "elementType": elem,
                "containsNull": True,
            }
        return self.type if self.type != "null" else "string"


@dataclass
class _StructAcc:
    fields: Dict[str, _FieldAcc] = field(default_factory=dict)
    samples: int = 0

    def observe(self, obj: dict) -> None:
        self.samples += 1
        for k, v in obj.items():
            self.fields.setdefault(k, _FieldAcc()).observe(v)

    def to_schema(self) -> dict:
        out = []
        for name, acc in self.fields.items():
            out.append({
                "name": name,
                "type": acc.to_schema_type(),
                "nullable": acc.nullable or acc.seen < self.samples,
                "metadata": acc.sampled_metadata(),
            })
        return {"type": "struct", "fields": out}


def infer_schema(events: List[dict]) -> dict:
    """Merge JSON event shapes into one struct schema
    (reference: SchemaGenerator merge, Engine.cs:23-65)."""
    acc = _StructAcc()
    for e in events:
        if isinstance(e, dict):
            acc.observe(e)
    return acc.to_schema()


class SchemaInferenceManager:
    """Sample a source for N seconds, emit schema + sample blob.

    reference: SchemaInferenceManager.GetInputSchema +
    EventhubMessageBus.GetSampleEvents(seconds).
    """

    def __init__(self, runtime_storage=None):
        self.runtime = runtime_storage

    def sample_events(
        self, source, seconds: float = 5.0, max_events: int = 1000
    ) -> List[dict]:
        """Pull events from a runtime StreamingSource for ``seconds``."""
        events: List[dict] = []
        deadline = time.time() + seconds
        while time.time() < deadline and len(events) < max_events:
            rows, _offsets = source.poll(max_events - len(events))
            source.ack()
            events.extend(rows)
            if not rows:
                time.sleep(0.05)
        return events

    def get_input_schema(
        self,
        source=None,
        events: Optional[List[dict]] = None,
        flow_name: str = "",
        seconds: float = 5.0,
        max_events: int = 1000,
    ) -> dict:
        """Returns {"Schema": <schema json str>, "Samples": [...]} and, when
        runtime storage is configured, persists the sample blob for
        LiveQuery kernel init (the reference writes it to the flow's
        sample folder)."""
        if events is None:
            if source is None:
                raise ValueError("either source or events required")
            events = self.sample_events(source, seconds, max_events)
        schema = infer_schema(events)
        result = {
            "Schema": json.dumps(schema),
            "Samples": events[:max_events],
            "EventsSampled": len(events),
        }
        if self.runtime is not None and flow_name:
            self.runtime.save_file(
                f"{flow_name}/samples/sample.json",
                "\n".join(json.dumps(e) for e in events),
            )
        return result
