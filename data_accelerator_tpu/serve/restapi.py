"""Control-plane REST service.

reference: the four DataX.Flow micro-services + gateway, collapsed into
one process (the reference's one-box does the same — Flow.ManagementService
hosts everything in DeploymentLocal/Dockerfile):

- ``api/flow/*``      — Flow.ManagementService
  (FlowManagementController.cs:51-249: save, generateconfigs, get,
  getall, startjobs, stopjobs, restartjobs, schedulebatch, job/*)
- ``api/userqueries/*`` — SqlParser schema + codegen endpoints
  (FlowManagementController.cs:246-301)
- ``api/inputdata/*`` — Flow.SchemaInferenceService
  (SchemaInferenceController.cs:33-52)
- ``api/kernel*``     — Flow.InteractiveQueryService
  (InteractiveQueryController.cs:33-171)
- role gate          — DataX.Gateway role/whitelist check
  (GatewayController.cs:113-148): callers present roles in the
  ``X-DataX-Roles`` header; writer endpoints need the writer role.

Responses use the DataX.Contract ApiResult envelope:
``{"result": ...}`` on success, ``{"error": {"message": ...}}`` on
failure. Run: ``python -m data_accelerator_tpu.serve [port=5000]``.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..compile.codegen import CodegenEngine
from ..lq.service import LiveQueryService
from ..lq.session import AdmissionRejected, SessionManager
from ..obs import tracing
from .flowservice import FlowOperation
from .jobs import FleetAdmissionError
from .livequery import KernelService
from .schemainference import SchemaInferenceManager
from .sqlanalyzer import SqlAnalyzer

logger = logging.getLogger(__name__)

ROLE_READER = "DataXReader"
ROLE_WRITER = "DataXWriter"


class ApiError(Exception):
    def __init__(self, message: str, status: int = 400):
        super().__init__(message)
        self.status = status


class DataXApi:
    """Route table + handlers over the service objects (transport-free,
    so tests can call ``dispatch`` directly)."""

    def __init__(
        self,
        flow_ops: FlowOperation,
        kernels: Optional[KernelService] = None,
        require_roles: bool = False,
        tracer: Optional[tracing.Tracer] = None,
        livequery: Optional[LiveQueryService] = None,
        fleet=None,
    ):
        # control-plane request tracing: each dispatched route becomes a
        # `rest/<path>` trace whose id flows through job submit ->
        # admission -> spawned host conf (telemetry.parenttrace), so the
        # flight recorder can show one tree from the designer click to
        # the batch spans it caused. None = tracing off (default).
        self.tracer = tracer
        self.flow_ops = flow_ops
        # kernel pool shares one persistent compile cache under the
        # runtime root: repeated kernel creates (and restarts of the
        # whole control plane) deserialize query compiles instead of
        # re-tracing them — the warm-LiveQuery-pool half of the AOT
        # compile path (runtime/processor.py process.compile.*)
        from ..compile.aotcache import compile_conf_for

        compile_conf = compile_conf_for(os.path.join(
            flow_ops.runtime.resolve("livequery"), "compilecache"
        ))
        # ONE session registry behind both interactive surfaces: the
        # legacy designer kernels (kernel/* routes, TTL-reaped now) and
        # the multi-tenant serving plane (lq/* routes, quota'd). The
        # in-process LiveQuery default runs tickless (each execute
        # flushes its own dispatch tick — still coalescing whatever
        # queued concurrently); ``serve/__main__`` passes a ticker'd
        # instance for the real server.
        if kernels is not None:
            self.kernels = kernels
            self.livequery = livequery or LiveQueryService(
                session_manager=kernels.sessions,
                compile_conf=compile_conf,
            )
        else:
            self.livequery = livequery or LiveQueryService(
                session_manager=SessionManager(),
                compile_conf=compile_conf,
            )
            self.kernels = KernelService(
                runtime_storage=flow_ops.runtime,
                compile_conf=compile_conf,
                session_manager=self.livequery.sessions,
            )
        # fleet telemetry rollup (obs/fleetview.py): /fleet/* routes
        # read it; None = fleet plane not wired (404s explain why)
        self.fleet = fleet
        self.schema_inference = SchemaInferenceManager(flow_ops.runtime)
        self.analyzer = SqlAnalyzer()
        self.codegen = CodegenEngine()
        self.require_roles = require_roles
        # (method, path) -> (handler, needs_writer)
        self.routes: Dict[Tuple[str, str], Tuple[Callable, bool]] = {}
        self._register()

    def _register(self) -> None:
        r = self.routes
        r[("POST", "flow/save")] = (self._flow_save, True)
        r[("POST", "flow/validate")] = (self._flow_validate, False)
        r[("POST", "flow/generateconfigs")] = (self._flow_generate, True)
        r[("POST", "flow/startjobs")] = (self._flow_start, True)
        r[("POST", "flow/stopjobs")] = (self._flow_stop, True)
        r[("POST", "flow/restartjobs")] = (self._flow_restart, True)
        r[("POST", "flow/schedulebatch")] = (self._flow_schedulebatch, True)
        r[("POST", "flow/delete")] = (self._flow_delete, True)
        r[("GET", "flow/get")] = (self._flow_get, False)
        r[("GET", "flow/getall")] = (self._flow_getall, False)
        r[("GET", "flow/getall/min")] = (self._flow_getall_min, False)
        r[("GET", "job/getall")] = (self._job_getall, False)
        r[("GET", "job/get")] = (self._job_get, False)
        r[("POST", "job/getbynames")] = (self._job_getbynames, False)
        r[("POST", "job/syncall")] = (self._job_syncall, True)
        r[("POST", "userqueries/schema")] = (self._userquery_schema, False)
        r[("POST", "userqueries/codegen")] = (self._userquery_codegen, False)
        r[("POST", "inputdata/inferschema")] = (self._infer_schema, True)
        r[("POST", "inputdata/refreshsample")] = (self._infer_schema, True)
        r[("POST", "kernel")] = (self._kernel_create, True)
        r[("POST", "kernel/refresh")] = (self._kernel_refresh, True)
        r[("POST", "kernel/executequery")] = (self._kernel_execute, False)
        r[("POST", "kernel/delete")] = (self._kernel_delete, True)
        r[("POST", "kernels/deleteall")] = (self._kernels_deleteall, True)
        r[("GET", "kernels/list")] = (self._kernels_list, False)
        # LiveQuery serving plane (lq/): multi-tenant sessions with
        # micro-batched dispatch; quota rejections surface as 429 +
        # Retry-After (see _dispatch_traced / DataXApiService._respond)
        r[("POST", "lq/session")] = (self._lq_session_create, False)
        r[("POST", "lq/execute")] = (self._lq_execute, False)
        r[("POST", "lq/session/close")] = (self._lq_session_close, False)
        r[("GET", "lq/sessions")] = (self._lq_sessions_list, False)
        r[("GET", "lq/stats")] = (self._lq_stats, False)
        # fleet telemetry plane (obs/fleetview.py): the cross-replica
        # rollup + lineage + DX54x delivery audit; /fleet/flows/<name>
        # is rewritten onto the ?flow= form in dispatch()
        r[("GET", "fleet/metrics")] = (self._fleet_metrics, False)
        r[("GET", "fleet/flows")] = (self._fleet_flow, False)

    # -- dispatch --------------------------------------------------------
    def dispatch(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        query: Optional[dict] = None,
        roles: Optional[list] = None,
    ) -> Tuple[int, dict]:
        """Returns (http_status, ApiResult envelope)."""
        path = path.strip("/")
        if path.startswith("api/"):
            path = path[len("api/"):]
        # gateway/website-style paths carry the target service as the
        # first segment (api/{service}/{route}); this single process
        # serves all four service families, so drop it when present
        head, _, rest = path.partition("/")
        if head in (
            "flow", "interactivequery", "schemainference", "livedata",
            "livequery",
        ) and (method.upper(), path) not in self.routes:
            path = rest
        # path-parameter form of the fleet flow route: the route table
        # is exact-match, so /fleet/flows/<flow> rewrites onto the
        # query-param handler
        if path.startswith("fleet/flows/"):
            query = dict(query or {})
            query["flow"] = [path[len("fleet/flows/"):]]
            path = "fleet/flows"
        entry = self.routes.get((method.upper(), path))
        if entry is None:
            return 404, {"error": {"message": f"unknown route {method} {path}"}}
        handler, needs_writer = entry
        if self.require_roles:
            roles = roles or []
            if ROLE_READER not in roles and ROLE_WRITER not in roles:
                return 401, {"error": {"message": "caller has no DataX role"}}
            if needs_writer and ROLE_WRITER not in roles:
                return 403, {"error": {"message": "writer role required"}}
        ctx = (
            self.tracer.begin(f"rest/{path}", method=method.upper())
            if self.tracer is not None else None
        )
        status, payload = self._dispatch_traced(
            handler, ctx, method, path, body, query
        )
        if ctx is not None:
            ctx.end(status=status)
        return status, payload

    def _dispatch_traced(
        self, handler, ctx, method, path, body, query,
    ) -> Tuple[int, dict]:
        try:
            with (ctx.activate() if ctx is not None
                  else contextlib.nullcontext()):
                result = handler(body or {}, query or {})
            return 200, {"result": result}
        except ApiError as e:
            return e.status, {"error": {"message": str(e)}}
        except AdmissionRejected as e:
            # serving-plane quota/capacity rejection: typed 429 the
            # caller can back off on — the rejected call NEVER queued,
            # so it consumed no kernel compile and no device dispatch.
            # DataXApiService turns retryAfterSeconds into the
            # Retry-After response header.
            return 429, {"error": e.to_dict()}
        except FleetAdmissionError as e:
            # fleet admission gate: the submit conflicts with the
            # current fleet state (DX400/401/410/411) — a client
            # problem, not a server fault; the diagnostics are the body
            return 409, {"error": {
                "message": str(e),
                "codes": [d.code for d in e.diagnostics],
                "diagnostics": [d.to_dict() for d in e.diagnostics],
            }}
        except KeyError as e:
            return 404, {"error": {"message": str(e)}}
        except Exception as e:  # noqa: BLE001 — API boundary
            logger.exception("api error on %s %s", method, path)
            return 500, {"error": {"message": f"{type(e).__name__}: {e}"}}

    # -- helpers ---------------------------------------------------------
    @staticmethod
    def _flow_name(body: dict, query: dict) -> str:
        name = (
            body.get("flowName") or body.get("name")
            or (query.get("flowName") or [None])[0]
            or (query.get("flowname") or [None])[0]
        )
        if isinstance(name, list):
            name = name[0]
        if not name:
            raise ApiError("flowName required")
        return name

    # -- flow ------------------------------------------------------------
    def _flow_save(self, body, query):
        gui = body.get("gui") or body
        doc = self.flow_ops.save_flow(gui)
        return {"name": doc["name"], "displayName": doc.get("displayName")}

    def _flow_validate(self, body, query):
        """Static analysis; same diagnostics as the analysis CLI (shared
        ``analysis.analyze_flow`` implementation). Body: a flow config
        (gui JSON / full doc), or ``{"flowName": ...}`` for a saved one.
        ``"device": true`` adds the device-plan tier (the CLI's
        ``--device``): DX2xx lints merged into the diagnostics plus a
        ``device`` cost report (per-stage HBM/FLOP/ICI); optional
        ``"chips": N`` sets the ICI model's chip count. ``"udfs":
        true`` adds the UDF tier (the CLI's ``--udfs``): DX3xx
        tracing-safety/purity lints merged into the diagnostics plus a
        ``udfs`` summary of the functions analyzed. ``"fleet": true``
        adds the fleet tier (the CLI's ``--fleet``): the candidate flow
        is analyzed against every currently registered flow — DX4xx
        capacity/interference lints merged into the diagnostics plus a
        ``fleet`` placement plan (chip -> flows -> packed HBM/headroom);
        optional ``"fleetSpec": {...}`` overrides the default fleet.
        ``"compile": true`` adds the compile-surface tier (the CLI's
        ``--compile``): DX6xx finiteness/stability lints merged into
        the diagnostics plus a ``compile`` section carrying the AOT
        compile manifest; optional ``"compileManifest": {...}`` checks
        a previously emitted manifest for drift (DX602/DX603).
        ``"mesh": true`` adds the mesh-sharding tier (the CLI's
        ``--mesh``): DX7xx partition lints merged into the diagnostics
        plus a ``mesh`` section carrying the sharding plan (stage ->
        axis -> per-chip bytes -> ICI bytes); the same ``"chips": N``
        body field sets the mesh size. ``"race": true`` adds the
        buffer-lifetime/concurrency tier (the CLI's ``--race``): the
        DX8xx lints over the ENGINE modules the flow deploys onto,
        merged into the diagnostics plus a ``race`` section (modules
        analyzed, pinned zero-copy sites, owner handoffs).
        ``"protocol": true`` adds the exactly-once delivery-protocol
        tier (the CLI's ``--protocol``): the DX90x ordering lints over
        the engine modules plus the rescale handoff, merged into the
        diagnostics plus a ``protocol`` section (modules analyzed,
        effect events, pinned post-commit / requeue-upstream sites).
        ``"conf": true`` adds the configuration-lattice tier (the
        CLI's ``--conf``): the DX10xx conf lints — engine read sites
        and generation-produced keys checked against the typed conf
        registry, plus type/bounds and incompatible-knob checks on
        THIS flow's effective conf — merged into the diagnostics plus
        a ``conf`` section (modules scanned, read sites/keys, produced
        keys, registry rows).
        ``"all": true`` runs every tier in one call — one merged report, one
        ``schemaVersion``, the CI single-invocation path."""
        flow = body.get("flow") or body.get("gui")
        if flow is None and (body.get("flowName") or body.get("name")) \
                and not body.get("process") and not body.get("input"):
            flow = self.flow_ops.get_flow(self._flow_name(body, query))
            if flow is None:
                raise ApiError("flow not found", status=404)
        if flow is None:
            flow = body
        report = self.flow_ops.validate_flow(flow)
        all_tiers = bool(body.get("all"))
        want_device = all_tiers or body.get("device")
        want_udfs = all_tiers or body.get("udfs")
        want_fleet = all_tiers or body.get("fleet")
        want_compile = all_tiers or body.get("compile")
        want_mesh = all_tiers or body.get("mesh")
        want_race = all_tiers or body.get("race")
        want_protocol = all_tiers or body.get("protocol")
        want_conf = all_tiers or body.get("conf")
        if not (want_device or want_udfs or want_fleet or want_compile
                or want_mesh or want_race or want_protocol
                or want_conf):
            return report.to_dict()
        from ..analysis import (
            ChipCountError,
            combined_report_dict,
            parse_chip_count,
        )

        # one shared, typed chip-count parser for the device ICI model
        # and the mesh plan (the CLI's --chips counterpart)
        try:
            chips = parse_chip_count(body.get("chips"), '"chips"')
        except ChipCountError as e:
            raise ApiError(str(e))
        device = (
            self.flow_ops.validate_flow_device(flow, chips=chips)
            if want_device else None
        )
        udfs = (
            self.flow_ops.validate_flow_udfs(flow) if want_udfs else None
        )
        fleet = (
            self.flow_ops.validate_flow_fleet(
                flow, spec=body.get("fleetSpec")
            )
            if want_fleet else None
        )
        comp = (
            self.flow_ops.validate_flow_compile(
                flow, manifest=body.get("compileManifest")
            )
            if want_compile else None
        )
        mesh = (
            self.flow_ops.validate_flow_mesh(flow, chips=chips)
            if want_mesh else None
        )
        race = (
            self.flow_ops.validate_flow_race(flow) if want_race else None
        )
        protocol = (
            self.flow_ops.validate_flow_protocol(flow)
            if want_protocol else None
        )
        conf = (
            self.flow_ops.validate_flow_conf(flow) if want_conf else None
        )
        return combined_report_dict(
            report, device, udfs, fleet, compile_surface=comp, mesh=mesh,
            race=race, protocol=protocol, conf=conf,
        )

    def _flow_generate(self, body, query):
        res = self.flow_ops.generate_configs(self._flow_name(body, query))
        if not res.ok:
            raise ApiError("; ".join(res.errors), status=500)
        return {
            "flowName": res.flow_name,
            "jobNames": res.job_names,
            "confPaths": res.conf_paths,
        }

    def _flow_start(self, body, query):
        return self.flow_ops.start_jobs(
            self._flow_name(body, query), batches=body.get("batches")
        )

    def _flow_stop(self, body, query):
        return self.flow_ops.stop_jobs(self._flow_name(body, query))

    def _flow_restart(self, body, query):
        return self.flow_ops.restart_jobs(
            self._flow_name(body, query), batches=body.get("batches")
        )

    def _flow_schedulebatch(self, body, query):
        return self.flow_ops.schedule_batch(self._flow_name(body, query))

    def _flow_delete(self, body, query):
        """Cascade delete incl. the flow's live kernels + LQ sessions
        (DataX.Flow.DeleteHelper deletes configs/checkpoints/kernels)."""
        name = self._flow_name(body, query)
        self.kernels.delete_kernels(name)
        self.livequery.close_flow(name)
        return {"deleted": self.flow_ops.delete_flow(name)}

    def _flow_get(self, body, query):
        doc = self.flow_ops.get_flow(self._flow_name(body, query))
        if doc is None:
            raise ApiError("flow not found", status=404)
        return doc

    def _flow_getall(self, body, query):
        return self.flow_ops.get_all_flows()

    def _flow_getall_min(self, body, query):
        return [
            {
                "name": d["name"],
                "displayName": d.get("displayName"),
                "jobNames": d.get("jobNames") or [],
            }
            for d in self.flow_ops.get_all_flows()
        ]

    # -- jobs ------------------------------------------------------------
    def _job_getall(self, body, query):
        return self.flow_ops.registry.get_all()

    def _job_get(self, body, query):
        name = (query.get("jobName") or [None])[0] or body.get("jobName")
        if not name:
            raise ApiError("jobName required")
        job = self.flow_ops.registry.get(name)
        if job is None:
            raise ApiError("job not found", status=404)
        return job

    def _job_getbynames(self, body, query):
        names = body.get("jobNames") or []
        return [self.flow_ops.registry.get(n) for n in names]

    def _job_syncall(self, body, query):
        return self.flow_ops.sync_jobs()

    # -- user queries ----------------------------------------------------
    def _userquery_schema(self, body, query):
        res = self.analyzer.analyze(
            body.get("query") or "",
            input_columns=body.get("inputColumns") or [],
        )
        return {
            "tables": [
                {
                    "name": t.name,
                    "columns": t.columns,
                    "dependsOn": t.depends_on,
                }
                for t in res.tables
            ],
            "errors": res.errors,
        }

    def _userquery_codegen(self, body, query):
        # live validation must match generation: TIMEWINDOW targets
        # check against the saved flow's projected tables when known
        windowable = None
        name = body.get("name") or ""
        doc = self.flow_ops.get_flow(name) if name else None
        if doc:
            windowable = {"DataXProcessedInput"}
            gui = doc.get("gui") or {}
            for src in (gui.get("input") or {}).get("sources") or []:
                sname = src.get("id") or src.get("name")
                if sname:
                    windowable.add(
                        (src.get("properties") or {}).get("target") or sname
                    )
        rc = self.codegen.generate_code(
            body.get("query") or "",
            json.dumps(body.get("rules") or []),
            name,
            windowable_tables=windowable,
        )
        return {
            "code": rc.code,
            "outputs": rc.outputs,
            "timeWindows": rc.time_windows,
            "accumulationTables": rc.accumulation_tables,
        }

    # -- schema inference ------------------------------------------------
    def _infer_schema(self, body, query):
        name = body.get("name") or body.get("flowName") or ""
        events = body.get("events")
        seconds = float(body.get("seconds") or 2.0)
        if events is None:
            events = self._sample_from_flow(name, seconds, body)
        return self.schema_inference.get_input_schema(
            events=events, flow_name=name
        )

    def _sample_from_flow(self, name: str, seconds: float, body: dict):
        """Sample from the flow's configured input (local source built
        from the designer's schema — the one-box path; remote bus
        sampling plugs in here)."""
        from ..core.schema import Schema
        from ..runtime.sources import LocalSource

        schema_json = body.get("inputSchema")
        if not schema_json and name:
            doc = self.flow_ops.get_flow(name)
            if doc:
                schema_json = (
                    ((doc.get("gui") or {}).get("input") or {})
                    .get("properties") or {}
                ).get("inputSchemaFile")
        if not schema_json:
            raise ApiError(
                "no events supplied and no input schema available to sample"
            )
        src = LocalSource(Schema.from_spark_json(schema_json))
        return self.schema_inference.sample_events(src, seconds=seconds)

    # -- kernels ---------------------------------------------------------
    def _kernel_body(self, body) -> dict:
        name = body.get("name") or body.get("flowName") or ""
        schema_json = body.get("inputSchema")
        normalization = body.get("normalizationSnippet") or "Raw.*"
        if not schema_json and name:
            doc = self.flow_ops.get_flow(name)
            if doc:
                props = (
                    ((doc.get("gui") or {}).get("input") or {})
                    .get("properties") or {}
                )
                schema_json = props.get("inputSchemaFile")
                normalization = (
                    body.get("normalizationSnippet")
                    or props.get("normalizationSnippet")
                    or "Raw.*"
                )
        if not schema_json:
            raise ApiError("inputSchema required (or a saved flow name)")
        sample_rows = body.get("sampleRows")
        if sample_rows is None and not self.kernels.has_sample(name):
            # no persisted sample blob (schema inference never ran):
            # local/one-box flows sample from the simulated source the
            # job itself would use, so LiveQuery still has input rows
            from ..core.schema import Schema
            from ..utils.datagen import DataGenerator

            try:
                gen = DataGenerator(Schema.from_spark_json(schema_json))
                sample_rows = gen.random_rows(50)
            except (ValueError, KeyError):
                sample_rows = None
        return {
            "flow_name": name,
            "schema_json": schema_json,
            "normalization": normalization,
            "sample_rows": sample_rows,
            # sanitizer opt-in for interactive UDF runs ("debug": true
            # or {"nans": "true", "tracerleaks": "true"}) — the
            # process.debug conf block, LiveQuery edition
            "debug": body.get("debug"),
        }


    def _kernel_create(self, body, query):
        kw = self._kernel_body(body)
        kid = self.kernels.create_kernel(**kw)
        return {"kernelId": kid}

    def _kernel_refresh(self, body, query):
        """Recycle the flow's kernels and create a fresh one
        (InteractiveQueryController kernel/refresh)."""
        kw = self._kernel_body(body)
        self.kernels.delete_kernels(kw["flow_name"])
        kid = self.kernels.create_kernel(**kw)
        return {"kernelId": kid}

    def _kernel_execute(self, body, query):
        kid = body.get("kernelId")
        if not kid:
            raise ApiError("kernelId required")
        return self.kernels.execute(
            kid, body.get("query") or "", int(body.get("maxRows") or 100)
        )

    def _kernel_delete(self, body, query):
        kid = body.get("kernelId")
        if not kid:
            raise ApiError("kernelId required")
        return {"deleted": self.kernels.delete_kernel(kid)}

    def _kernels_deleteall(self, body, query):
        return {"deleted": self.kernels.delete_kernels(body.get("flowName"))}

    def _kernels_list(self, body, query):
        return self.kernels.list_kernels()

    # -- LiveQuery serving plane (lq/) -----------------------------------
    def _lq_session_create(self, body, query):
        """Create a tenant session. Flow fields resolve exactly like a
        legacy kernel create (saved flow name, inline schema, persisted
        or generated sample); per-tenant session quotas are enforced
        here — over-quota tenants get 429 + Retry-After, not a kernel."""
        kw = self._kernel_body(body)
        return self.livequery.create_session(
            tenant=str(body.get("tenant") or "default"),
            flow_name=kw["flow_name"],
            schema_json=kw["schema_json"],
            normalization=kw["normalization"],
            sample_rows=kw["sample_rows"],
            debug=kw["debug"],
        )

    def _lq_execute(self, body, query):
        sid = body.get("sessionId")
        if not sid:
            raise ApiError("sessionId required")
        return self.livequery.execute(
            sid, body.get("query") or "", int(body.get("maxRows") or 100)
        )

    def _lq_session_close(self, body, query):
        sid = body.get("sessionId")
        if not sid:
            raise ApiError("sessionId required")
        return {"closed": self.livequery.close_session(sid)}

    def _lq_sessions_list(self, body, query):
        tenant = (query.get("tenant") or [None])[0] or body.get("tenant")
        return self.livequery.list_sessions(tenant=tenant)

    def _lq_stats(self, body, query):
        return self.livequery.snapshot()

    # -- fleet telemetry plane -------------------------------------------
    def _require_fleet(self):
        if self.fleet is None:
            raise ApiError(
                "fleet view not configured (run the control plane "
                "with an object store so replicas have a frame plane)",
                503,
            )
        return self.fleet

    def _fleet_metrics(self, body, query):
        fleet = self._require_fleet()
        fleet.refresh()
        return fleet.summary()

    def _fleet_flow(self, body, query):
        fleet = self._require_fleet()
        flow = (query.get("flow") or [None])[0]
        if not flow:
            raise ApiError("flow name required: /fleet/flows/<flow>")
        fleet.refresh()
        if flow not in fleet.flows():
            raise ApiError(f"no telemetry frames for flow {flow!r}", 404)
        payload = fleet.fleet_metrics(flow)
        output = (query.get("output") or [None])[0]
        if output:
            payload["audit"] = fleet.audit(flow, output=output)
        return payload


class DataXApiService:
    """HTTP host for DataXApi (ThreadingHTTPServer)."""

    def __init__(self, api: DataXApi, host: str = "127.0.0.1", port: int = 5000):
        self.api = api
        api_ref = api

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                logger.debug("http %s", fmt % args)

            def _respond(self, status: int, payload: dict) -> None:
                data = json.dumps(payload, default=str).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                if status == 429:
                    # quota rejections carry a typed retry hint
                    # (lq/session.py AdmissionRejected.to_dict) —
                    # surface it as the standard backoff header
                    retry = (payload.get("error") or {}).get(
                        "retryAfterSeconds"
                    )
                    if isinstance(retry, (int, float)):
                        self.send_header(
                            "Retry-After", str(max(1, int(-(-retry // 1))))
                        )
                self.end_headers()
                self.wfile.write(data)

            def _roles(self):
                hdr = self.headers.get("X-DataX-Roles") or ""
                return [r.strip() for r in hdr.split(",") if r.strip()]

            def _handle(self, method: str) -> None:
                parsed = urlparse(self.path)
                body = None
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    try:
                        body = json.loads(self.rfile.read(length) or b"{}")
                    except json.JSONDecodeError:
                        self._respond(
                            400, {"error": {"message": "invalid JSON body"}}
                        )
                        return
                status, payload = api_ref.dispatch(
                    method,
                    parsed.path,
                    body=body,
                    query=parse_qs(parsed.query),
                    roles=self._roles(),
                )
                self._respond(status, payload)

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        logger.info("DataX API listening on :%d", self.port)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def serve_forever(self) -> None:
        self._server.serve_forever()
