"""Control plane: flow lifecycle, config generation, job management.

The TPU-native analog of the reference's Services/ layer
(DataX.Config + DataX.Flow.* + DataX.Gateway): design-time flow
documents in, runnable flat ``datax.job.*`` confs and managed engine
jobs out.
"""

from .templating import TokenDictionary
from .storage import (
    JobRegistry,
    LocalDesignTimeStorage,
    LocalRuntimeStorage,
    ObjectDesignTimeStorage,
    ObjectRuntimeStorage,
)
from .objectstore import ObjectStoreClient, ObjectStoreServer
from .flowbuilder import FlowConfigBuilder, RuleDefinitionGenerator
from .generation import RuntimeConfigGeneration
from .jobs import (
    JobOperation,
    JobState,
    K8sJobClient,
    LocalJobClient,
    TpuJobClient,
    make_job_client,
)
from .flowservice import FlowOperation
from .schemainference import SchemaInferenceManager, infer_schema
from .sqlanalyzer import SqlAnalyzer
from .livequery import KernelService
from .scenario import Scenario, ScenarioContext
from .restapi import DataXApi, DataXApiService

__all__ = [
    "TokenDictionary",
    "JobRegistry",
    "LocalDesignTimeStorage",
    "LocalRuntimeStorage",
    "ObjectDesignTimeStorage",
    "ObjectRuntimeStorage",
    "ObjectStoreClient",
    "ObjectStoreServer",
    "FlowConfigBuilder",
    "RuleDefinitionGenerator",
    "RuntimeConfigGeneration",
    "JobOperation",
    "JobState",
    "K8sJobClient",
    "LocalJobClient",
    "TpuJobClient",
    "make_job_client",
    "FlowOperation",
    "SchemaInferenceManager",
    "infer_schema",
    "SqlAnalyzer",
    "KernelService",
    "Scenario",
    "ScenarioContext",
    "DataXApi",
    "DataXApiService",
]
