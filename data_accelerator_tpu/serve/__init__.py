"""Control plane: flow lifecycle, config generation, job management.

The TPU-native analog of the reference's Services/ layer
(DataX.Config + DataX.Flow.* + DataX.Gateway): design-time flow
documents in, runnable flat ``datax.job.*`` confs and managed engine
jobs out.
"""

from .templating import TokenDictionary
from .storage import LocalDesignTimeStorage, LocalRuntimeStorage
from .flowbuilder import FlowConfigBuilder, RuleDefinitionGenerator
from .generation import RuntimeConfigGeneration

__all__ = [
    "TokenDictionary",
    "LocalDesignTimeStorage",
    "LocalRuntimeStorage",
    "FlowConfigBuilder",
    "RuleDefinitionGenerator",
    "RuntimeConfigGeneration",
]
