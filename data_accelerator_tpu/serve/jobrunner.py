"""JobRunner: scheduled e2e scenario suites as a liveness probe.

reference: Services/JobRunner/{JobRunner.cs,Jobs/*.cs} — an Azure WebJob
that periodically executes scenario suites (deploy flow, schema
inference + interactive query) against a *live* deployment via its REST
API, recording pass/fail per run (Jobs/DataXDeployJob.cs:21-45) — the
production smoke monitor. Scenarios themselves come from the
ScenarioTester step framework (serve/scenario.py here).

Results are (a) kept as a bounded in-memory history for the UI/API and
(b) emitted as metric points ``DATAX-JobRunner:<scenario>`` (1 pass /
0 fail) into the metric store so the dashboard can chart liveness —
the role AppInsights plays for the reference's runner.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from ..obs.metrics import MetricLogger
from .scenario import Scenario, ScenarioContext, ScenarioResult

logger = logging.getLogger(__name__)


class JobRunner:
    def __init__(
        self,
        scenarios: List[Scenario],
        interval_s: float = 300.0,
        metric_logger: Optional[MetricLogger] = None,
        context_factory: Optional[Callable[[], ScenarioContext]] = None,
        max_history: int = 200,
    ):
        self.scenarios = scenarios
        self.interval_s = interval_s
        self.metric_logger = metric_logger or MetricLogger("DATAX-JobRunner")
        self.context_factory = context_factory or ScenarioContext
        self.history: List[Dict] = []
        self.max_history = max_history
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run_once(self) -> List[ScenarioResult]:
        """Execute every scenario once, recording results + metrics."""
        results = []
        for sc in self.scenarios:
            t0 = time.time()
            result = sc.run(self.context_factory())
            elapsed_ms = (time.time() - t0) * 1000.0
            uts = int(t0 * 1000)
            record = {
                "scenario": sc.name,
                "success": result.success,
                "failedStep": result.failed_step,
                "elapsedMs": elapsed_ms,
                "uts": uts,
            }
            self.history.append(record)
            del self.history[: max(0, len(self.history) - self.max_history)]
            self.metric_logger.send_metric(
                sc.name, 1 if result.success else 0, uts
            )
            self.metric_logger.send_metric(f"{sc.name}-ElapsedMs", elapsed_ms, uts)
            (logger.info if result.success else logger.warning)(
                "scenario %s: %s (%.0f ms)%s",
                sc.name,
                "PASS" if result.success else "FAIL",
                elapsed_ms,
                "" if result.success else f" at step {result.failed_step}",
            )
            results.append(result)
        return results

    def start(self) -> None:
        def loop():
            while not self._stop.is_set():
                try:
                    self.run_once()
                except Exception:  # noqa: BLE001 — the probe must survive
                    logger.exception("job runner round failed")
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
