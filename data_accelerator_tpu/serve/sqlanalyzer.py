"""Design-time DataXQuery analysis for the query editor.

reference: DataX.Flow/DataX.Flow.SqlParser/{SqlParser,Analyzer}.cs —
parses the user's script into a table graph and projects each derived
table's output columns so the UI can offer intellisense
(SqlParser.cs:17-54). Reuses the production transform parser and SQL
parser — design-time analysis and runtime compilation cannot drift.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..compile.sqlparser import (
    BinOp,
    CaseWhen,
    Cast,
    Col,
    Func,
    InList,
    IsNull,
    Literal,
    Select,
    SqlParseError,
    Star,
    UnaryOp,
    parse_select,
)
from ..compile.transform_parser import (
    COMMAND_TYPE_QUERY,
    TransformParser,
)
from ..constants import DatasetName


@dataclass
class TableInfo:
    name: str
    columns: List[str] = field(default_factory=list)
    depends_on: List[str] = field(default_factory=list)
    sql: str = ""


@dataclass
class AnalysisResult:
    tables: List[TableInfo] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)

    def table(self, name: str) -> Optional[TableInfo]:
        for t in self.tables:
            if t.name == name:
                return t
        return None


def _expr_name(expr) -> str:
    """Display name for an un-aliased select item (Spark-ish)."""
    if isinstance(expr, Col):
        return expr.parts[-1]
    if isinstance(expr, Func):
        args = ", ".join(_expr_name(a) for a in expr.args)
        return f"{expr.name.lower()}({args})"
    if isinstance(expr, Literal):
        return str(expr.value)
    if isinstance(expr, (Cast, CaseWhen, BinOp, UnaryOp, InList, IsNull)):
        return "expr"
    return "expr"


_TIMEWINDOW_RE = re.compile(
    rf"\b{DatasetName.DataStreamProjection}_(\d+\w+)\b"
)


class SqlAnalyzer:
    """Analyze a transform script against known input columns."""

    def analyze(
        self,
        script: str,
        input_columns: Optional[List[str]] = None,
    ) -> AnalysisResult:
        res = AnalysisResult()
        known: Dict[str, List[str]] = {}
        base_cols = list(input_columns or [])
        known[DatasetName.DataStreamProjection] = base_cols
        try:
            parsed = TransformParser.parse(script.splitlines())
        except Exception as e:  # noqa: BLE001 — surfaced to the editor
            res.errors.append(str(e))
            return res

        for cmd in parsed.commands:
            if cmd.command_type != COMMAND_TYPE_QUERY or not cmd.name:
                continue
            # the runtime transform has semicolons stripped by codegen
            # (Engine.cs cleanup); tolerate them in raw editor text here
            sql = cmd.text.rstrip().rstrip(";")
            info = TableInfo(name=cmd.name, sql=sql)
            try:
                sel = parse_select(sql)
                info.depends_on = self._source_tables(sel)
                info.columns = self._project_columns(
                    sel, known, res.errors, cmd.name
                )
            except SqlParseError as e:
                res.errors.append(f"{cmd.name}: {e}")
            except Exception as e:  # noqa: BLE001
                res.errors.append(f"{cmd.name}: {e}")
            # windowed views of the input share its columns
            for dep in info.depends_on:
                if dep not in known and _TIMEWINDOW_RE.match(dep):
                    known[dep] = base_cols
            known[cmd.name] = info.columns
            res.tables.append(info)
        return res

    @staticmethod
    def _source_tables(sel: Select) -> List[str]:
        out = []
        if sel.from_table is not None:
            out.append(sel.from_table.name)
        for j in sel.joins:
            out.append(j.table.name)
        return out

    def _project_columns(
        self,
        sel: Select,
        known: Dict[str, List[str]],
        errors: Optional[List[str]] = None,
        table_name: str = "",
    ) -> List[str]:
        # FROM/JOIN scope in declaration order: binding (alias or name)
        # -> upstream columns, so ``t.*`` expands only t's columns and a
        # bare ``*`` is the union across every joined table
        scope: List[tuple] = []
        refs = ([sel.from_table] if sel.from_table is not None else [])
        refs += [j.table for j in sel.joins]
        for ref in refs:
            scope.append((ref.binding, ref.name, known.get(ref.name)))

        cols: List[str] = []
        explicit: set = set()
        for item in sel.items:
            if isinstance(item.expr, Star):
                for binding, name, upstream in scope:
                    if item.expr.table is not None and item.expr.table not in (
                        binding, name
                    ):
                        continue
                    for c in upstream or []:
                        if c not in cols:
                            cols.append(c)
                continue
            name = item.alias or _expr_name(item.expr)
            # "expr" is the display placeholder for unnamed expressions,
            # not a real output name — colliding there is not an error
            if name in explicit and name != "expr" and errors is not None:
                errors.append(
                    f"{table_name}: duplicate output column '{name}' — "
                    "alias one of the colliding select items"
                )
            explicit.add(name)
            if name not in cols:
                cols.append(name)
        return cols
