"""Job lifecycle: submit/stop/sync engine jobs on TPU hosts.

reference: DataX.Config's job layer —
- ``ISparkJobClient`` (DataX.Config/Client/ISparkJobClient.cs): the
  cluster-client interface (submit/stop/get state) with Livy, Databricks
  and local spark-submit implementations -> ``TpuJobClient`` here, with
  ``LocalJobClient`` spawning the streaming host as a child process
  (DataX.Config.Local/LocalSparkClient.cs:18-180 semantics: process
  handle is the job id, state from process liveness).
- ``SparkJobOperation`` (InternalService/SparkJobOperation.cs:42-268):
  start/stop/restart with bounded retries + state sync against the
  client -> ``JobOperation``.
- ``JobState`` (InternalService/JobState.cs): Idle/Starting/Running/
  Success/Error.

TPU flavor: a "cluster" is a set of TPU-VM hosts running the engine
process; the local client covers the one-box and single-host cases, and
the same interface carries a gRPC/SSH remote client for pods.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from .storage import JobRegistry

logger = logging.getLogger(__name__)


class JobState:
    Idle = "idle"
    Starting = "starting"
    Running = "running"
    Success = "success"
    Error = "error"


class TpuJobClient:
    """Cluster-client interface (ISparkJobClient analog)."""

    def submit(self, job: dict) -> dict:
        """Start the job; returns updated job record (clientId, state)."""
        raise NotImplementedError

    def stop(self, job: dict) -> dict:
        raise NotImplementedError

    def get_state(self, job: dict) -> str:
        raise NotImplementedError


class LocalJobClient(TpuJobClient):
    """Runs each job as a local engine process.

    reference: LocalSparkClient.cs:21,112-140 — spark-submit with
    ``--master local[*]``, pid tracked in the job record, state derived
    from process table. Here: ``python -m data_accelerator_tpu.runtime.host
    conf=<path>`` with optional env overrides (platform, chip count).
    """

    def __init__(self, log_dir: Optional[str] = None, env: Optional[dict] = None):
        self.log_dir = log_dir
        self.env = env or {}
        self._procs: Dict[str, subprocess.Popen] = {}

    def submit(self, job: dict) -> dict:
        name = job["name"]
        conf_path = job["confPath"]
        cmd = [
            sys.executable, "-m", "data_accelerator_tpu.runtime.host",
            f"conf={conf_path}",
        ]
        if job.get("batches"):
            cmd.append(f"batches={job['batches']}")
        env = {**os.environ, **self.env}
        stdout = subprocess.DEVNULL
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            stdout = open(os.path.join(self.log_dir, f"{name}.log"), "ab")
        try:
            proc = subprocess.Popen(
                cmd, stdout=stdout, stderr=subprocess.STDOUT, env=env,
                start_new_session=True,
            )
        finally:
            if stdout is not subprocess.DEVNULL:
                stdout.close()  # child keeps its inherited fd
        self._procs[name] = proc
        job["clientId"] = proc.pid
        job["state"] = JobState.Starting
        logger.info("submitted job %s pid=%d conf=%s", name, proc.pid, conf_path)
        return job

    def _proc(self, job: dict) -> Optional[subprocess.Popen]:
        return self._procs.get(job["name"])

    def stop(self, job: dict) -> dict:
        # forget the process so a later get_state doesn't read the
        # SIGTERM exit code as a job failure
        proc = self._procs.pop(job["name"], None)
        pid = job.get("clientId")
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        elif pid:
            # job from a previous service instance: signal by pid
            try:
                os.kill(int(pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        job["state"] = JobState.Idle
        job["clientId"] = None
        return job

    def get_state(self, job: dict) -> str:
        proc = self._proc(job)
        if proc is not None:
            rc = proc.poll()
            if rc is None:
                return JobState.Running
            return JobState.Success if rc == 0 else JobState.Error
        pid = job.get("clientId")
        if pid:
            try:
                os.kill(int(pid), 0)
                return JobState.Running
            except (ProcessLookupError, PermissionError):
                return JobState.Error
        return job.get("state") or JobState.Idle


class JobOperation:
    """Start/stop/restart with bounded retries + state sync.

    reference: SparkJobOperation.cs:42-268 (StartJobWithRetries /
    StopJobWithRetries / RestartJob / SyncJobState / SyncAllJobState).
    """

    def __init__(
        self,
        registry: JobRegistry,
        client: TpuJobClient,
        retries: int = 3,
        retry_interval_s: float = 0.5,
    ):
        self.registry = registry
        self.client = client
        self.retries = retries
        self.retry_interval_s = retry_interval_s

    # -- state sync ------------------------------------------------------
    def sync_job_state(self, job_name: str) -> dict:
        job = self.registry.get(job_name)
        if job is None:
            raise KeyError(f"job '{job_name}' not found")
        state = self.client.get_state(job)
        if state != job.get("state"):
            job["state"] = state
            self.registry.upsert(job)
        return job

    def sync_all(self) -> List[dict]:
        return [self.sync_job_state(j["name"]) for j in self.registry.get_all()]

    # -- lifecycle -------------------------------------------------------
    def start_job(self, job_name: str, batches: Optional[int] = None) -> dict:
        job = self.sync_job_state(job_name)
        if job["state"] in (JobState.Running, JobState.Starting):
            return job  # idempotent start (reference: StartJob short-circuit)
        if batches:
            job["batches"] = batches
        job = self.client.submit(job)
        self.registry.upsert(job)
        return job

    def start_job_with_retries(self, job_name: str, **kw) -> dict:
        return self._with_retries(lambda: self.start_job(job_name, **kw))

    def stop_job(self, job_name: str) -> dict:
        job = self.sync_job_state(job_name)
        if job["state"] not in (JobState.Running, JobState.Starting):
            return job
        job = self.client.stop(job)
        self.registry.upsert(job)
        return job

    def stop_job_with_retries(self, job_name: str) -> dict:
        return self._with_retries(lambda: self.stop_job(job_name))

    def restart_job(self, job_name: str, batches: Optional[int] = None) -> dict:
        self.stop_job_with_retries(job_name)
        # wait until the client reports not-running before resubmitting
        deadline = time.time() + 10
        while time.time() < deadline:
            if self.sync_job_state(job_name)["state"] not in (
                JobState.Running, JobState.Starting,
            ):
                break
            time.sleep(self.retry_interval_s)
        return self.start_job_with_retries(job_name, batches=batches)

    def wait_for_state(
        self, job_name: str, states, timeout_s: float = 30
    ) -> dict:
        """Poll sync until the job reaches one of ``states``
        (EnsureJobState semantics, SparkJobOperation.cs:229-266)."""
        deadline = time.time() + timeout_s
        job = self.sync_job_state(job_name)
        while job["state"] not in states and time.time() < deadline:
            time.sleep(self.retry_interval_s)
            job = self.sync_job_state(job_name)
        return job

    def _with_retries(self, fn):
        last: Optional[Exception] = None
        for _ in range(self.retries):
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — retried, then re-raised
                last = e
                logger.warning("job operation failed, retrying: %s", e)
                time.sleep(self.retry_interval_s)
        raise last  # type: ignore[misc]
