"""Job lifecycle: submit/stop/sync engine jobs on TPU hosts.

reference: DataX.Config's job layer —
- ``ISparkJobClient`` (DataX.Config/Client/ISparkJobClient.cs): the
  cluster-client interface (submit/stop/get state) with Livy, Databricks
  and local spark-submit implementations -> ``TpuJobClient`` here, with
  ``LocalJobClient`` spawning the streaming host as a child process
  (DataX.Config.Local/LocalSparkClient.cs:18-180 semantics: process
  handle is the job id, state from process liveness).
- ``SparkJobOperation`` (InternalService/SparkJobOperation.cs:42-268):
  start/stop/restart with bounded retries + state sync against the
  client -> ``JobOperation``.
- ``JobState`` (InternalService/JobState.cs): Idle/Starting/Running/
  Success/Error.

TPU flavor: a "cluster" is a set of TPU-VM hosts running the engine
process; the local client covers the one-box and single-host cases, and
the same interface carries a gRPC/SSH remote client for pods.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ..obs import tracing
from .storage import JobRegistry

logger = logging.getLogger(__name__)


class JobState:
    Idle = "idle"
    Starting = "starting"
    Running = "running"
    Success = "success"
    Error = "error"


# ---------------------------------------------------------------------------
# Fleet admission gate: the DX4xx analyzer as a runtime input
# ---------------------------------------------------------------------------
# the DX4xx codes that REJECT a submit: capacity infeasibility and the
# interference classes that corrupt state/streams (warnings — headroom,
# bandwidth, series/port conflicts — admit but surface in the record)
ADMISSION_GATE_CODES = ("DX400", "DX401", "DX410", "DX411")


class FleetAdmissionError(RuntimeError):
    """A job submit the fleet analyzer rejected. Carries the gating
    diagnostics; NOT retried by ``JobOperation._with_retries`` (the
    fleet state that rejected it does not change by retrying)."""

    def __init__(self, job_name: str, diagnostics):
        self.job_name = job_name
        self.diagnostics = list(diagnostics)
        super().__init__(
            f"job '{job_name}' rejected by fleet admission: "
            + "; ".join(d.render() for d in self.diagnostics)
        )


class FleetAdmissionGate:
    """Consults ``analysis/fleetcheck.py`` at job submission: the
    candidate flow is analyzed against every flow with running jobs,
    and a submit that would trigger DX400/DX401/DX410/DX411 is rejected
    with the diagnostic BEFORE any process spawns. The accepted
    placement (flow -> chip) is written onto the job record, and
    ``Fleet_*`` metrics (constants.MetricName) export the packed fleet
    state on every check/re-plan.

    The reference's cluster clients deployed blind (SURVEY §1 L3 —
    oversubscription was discovered by watching jobs die); this gate is
    the cost model ROADMAP item 2(b) promised, used as a runtime input.
    """

    def __init__(
        self,
        design_storage,
        registry: JobRegistry,
        spec=None,
        metric_logger=None,
    ):
        self.design = design_storage
        self.registry = registry
        self._spec = spec  # analysis.FleetSpec | None (default)
        self._metrics = metric_logger
        self.rejected_count = 0
        # flow name -> (flow-doc fingerprint, FlowFootprint): device
        # analysis per flow is the expensive step, so footprints are
        # cached and invalidated by config content
        self._footprints: Dict[str, tuple] = {}

    @property
    def spec(self):
        if self._spec is None:
            from ..analysis import FleetSpec

            self._spec = FleetSpec()
        return self._spec

    @property
    def metrics(self):
        if self._metrics is None:
            from ..obs.metrics import MetricLogger

            self._metrics = MetricLogger("DATAX-Fleet")
        return self._metrics

    # -- footprints ------------------------------------------------------
    def _footprint(self, name: str, doc: dict):
        from ..analysis import flow_footprint

        gui = doc.get("gui") if isinstance(doc.get("gui"), dict) else doc
        fingerprint = json.dumps(gui, sort_keys=True, default=str)
        cached = self._footprints.get(name)
        if cached is not None and cached[0] == fingerprint:
            return cached[1]
        fp = flow_footprint(doc, name=name)
        self._footprints[name] = (fingerprint, fp)
        return fp

    def _active_flow_names(self, exclude_flow: Optional[str] = None):
        names = []
        for rec in self.registry.get_all():
            if rec.get("state") not in (JobState.Running, JobState.Starting):
                continue
            flow = rec.get("flow")
            if not flow or flow == exclude_flow or flow in names:
                continue
            names.append(flow)
        return names

    # -- planning --------------------------------------------------------
    def plan(self, candidate_doc: Optional[dict] = None,
             exclude_flow: Optional[str] = None):
        """Fleet report over the flows with running jobs, optionally
        plus a candidate flow (excluded from the active set by name so
        a restart competes against the OTHERS, not its own old slot)."""
        from ..analysis import analyze_fleet

        footprints = []
        if candidate_doc is not None:
            footprints.append(self._footprint(exclude_flow or "", candidate_doc))
        for name in self._active_flow_names(exclude_flow=exclude_flow):
            doc = self.design.get_by_name(name)
            if doc is not None:
                footprints.append(self._footprint(name, doc))
        report = analyze_fleet(footprints, spec=self.spec)
        self._export_metrics(report)
        return report

    # -- the gate --------------------------------------------------------
    def admit(self, job: dict) -> dict:
        """Check one job's flow against the current fleet. On rejection
        the registry record carries the reason and a
        ``FleetAdmissionError`` raises before any process spawns; on
        admission the record carries the accepted placement."""
        flow_name = job.get("flow")
        doc = self.design.get_by_name(flow_name) if flow_name else None
        if doc is None:
            return job  # no flow doc to analyze (bare job record)
        with tracing.span("admission", job=job.get("name"), flow=flow_name):
            return self._admit_traced(job, doc, flow_name)

    def _admit_traced(self, job: dict, doc: dict, flow_name: str) -> dict:
        with tracing.span("placement"):
            report = self.plan(candidate_doc=doc, exclude_flow=flow_name)
        gating = [
            d for d in report.diagnostics
            if d.code in ADMISSION_GATE_CODES
            and (not d.table or flow_name in d.table.split("/"))
        ]
        if gating:
            self.rejected_count += 1
            job["admission"] = {
                "admitted": False,
                "codes": [d.code for d in gating],
                "reason": "; ".join(d.render() for d in gating),
            }
            self.registry.upsert(job)
            self.metrics.send_metric(
                "Fleet_AdmissionRejected_Count", self.rejected_count
            )
            raise FleetAdmissionError(job["name"], gating)
        chip = report.placement.chip_of(flow_name)
        fp = next(
            (f for f in report.footprints if f.name == flow_name), None
        )
        assignment = next(
            (c for c in report.placement.chips if c.chip == chip), None
        )
        job["admission"] = {"admitted": True, "codes": []}
        job["placement"] = {
            "chip": chip,
            "hbmBytes": fp.hbm_bytes if fp else None,
            "chipHbmBytes": assignment.hbm_bytes if assignment else None,
            "headroom": round(1 - assignment.utilization(self.spec), 6)
            if assignment else None,
            "fleetChips": self.spec.chips,
        }
        return job

    def admit_replicas(self, job: dict, count: int) -> None:
        """Vet an in-place rescale: would ``count`` replicas of this
        job's flow still pack onto the fleet? Replicas of ONE flow
        intentionally share checkpoint dirs / consumer groups / metric
        series (that's what makes them a competing-consumer group), so
        only the CAPACITY codes gate here — DX400/DX401 over ``count``
        copies of the flow's footprint plus every other active flow.
        Raises ``FleetAdmissionError`` BEFORE any process spawns."""
        import dataclasses

        flow_name = job.get("flow")
        doc = self.design.get_by_name(flow_name) if flow_name else None
        if doc is None or count <= 1:
            return
        from ..analysis import analyze_fleet

        base = self._footprint(flow_name, doc)
        footprints = [base] + [
            dataclasses.replace(
                base, name=f"{flow_name}~r{i}",
                # suffixed shadow footprints drop the shared-resource
                # claims so the interference lints don't see the
                # intentional sharing as cross-flow collisions
                dirs=set(), consumer_keys=set(), metric_series=set(),
                obs_port=None,
            )
            for i in range(2, count + 1)
        ]
        for name in self._active_flow_names(exclude_flow=flow_name):
            other = self.design.get_by_name(name)
            if other is not None:
                footprints.append(self._footprint(name, other))
        with tracing.span("rescale/placement", flow=flow_name, count=count):
            report = analyze_fleet(footprints, spec=self.spec)
        self._export_metrics(report)
        gating = [
            d for d in report.diagnostics
            if d.code in ("DX400", "DX401")
            and (not d.table or flow_name in d.table.split("/")
                 or any(f"{flow_name}~r" in part
                        for part in d.table.split("/")))
        ]
        if gating:
            self.rejected_count += 1
            job["rescale"] = {
                "requested": count,
                "admitted": False,
                "codes": [d.code for d in gating],
                "reason": "; ".join(d.render() for d in gating),
            }
            self.registry.upsert(job)
            self.metrics.send_metric(
                "Fleet_AdmissionRejected_Count", self.rejected_count
            )
            raise FleetAdmissionError(job["name"], gating)
        job["rescale"] = {"requested": count, "admitted": True, "codes": []}
        self.registry.upsert(job)

    def replan(self):
        """Recompute placement over the currently running flows (freed
        capacity becomes reusable) and refresh every active job
        record's ``placement``. Called by the scheduler's
        ``PlacementReplanner`` on job stop/start."""
        report = self.plan()
        by_chip = {
            name: c for c in report.placement.chips for name in c.flows
        }
        for rec in self.registry.get_all():
            flow = rec.get("flow")
            if flow not in by_chip or rec.get("state") not in (
                JobState.Running, JobState.Starting,
            ):
                continue
            c = by_chip[flow]
            rec["placement"] = {
                "chip": c.chip,
                "hbmBytes": next(
                    (f.hbm_bytes for f in report.footprints
                     if f.name == flow), None
                ),
                "chipHbmBytes": c.hbm_bytes,
                "headroom": round(1 - c.utilization(self.spec), 6),
                "fleetChips": self.spec.chips,
            }
            self.registry.upsert(rec)
        return report

    # -- metrics ---------------------------------------------------------
    def _export_metrics(self, report) -> None:
        try:
            plan = report.placement
            placed = sum(len(c.flows) for c in plan.chips)
            unplaced = (
                len(plan.unplaced) + len(plan.oversized)
                + len(plan.unanalyzed)
            )
            m = {
                "Fleet_Chips": self.spec.chips,
                "Fleet_FlowsPlaced": placed,
                "Fleet_FlowsUnplaced": unplaced,
                "Fleet_MaxChipUtilization": max(
                    (c.utilization(self.spec) for c in plan.chips),
                    default=0.0,
                ),
            }
            for c in plan.chips:
                if c.flows:
                    m[f"Fleet_Chip{c.chip}_HbmBytes"] = c.hbm_bytes
                    m[f"Fleet_Chip{c.chip}_Utilization"] = (
                        c.utilization(self.spec)
                    )
            self.metrics.send_batch_metrics(m)
        except Exception:  # noqa: BLE001 — metrics must never gate a job
            logger.exception("fleet metric export failed")


class TpuJobClient:
    """Cluster-client interface (ISparkJobClient analog)."""

    def submit(self, job: dict) -> dict:
        """Start the job; returns updated job record (clientId, state)."""
        raise NotImplementedError

    def stop(self, job: dict) -> dict:
        raise NotImplementedError

    def get_state(self, job: dict) -> str:
        raise NotImplementedError


class LocalJobClient(TpuJobClient):
    """Runs each job as a local engine process.

    reference: LocalSparkClient.cs:21,112-140 — spark-submit with
    ``--master local[*]``, pid tracked in the job record, state derived
    from process table. Here: ``python -m data_accelerator_tpu.runtime.host
    conf=<path>`` with optional env overrides (platform, chip count).
    """

    def __init__(self, log_dir: Optional[str] = None, env: Optional[dict] = None):
        self.log_dir = log_dir
        self.env = env or {}
        self._procs: Dict[str, subprocess.Popen] = {}

    def submit(self, job: dict) -> dict:
        name = job["name"]
        conf_path = job["confPath"]
        cmd = [
            sys.executable, "-m", "data_accelerator_tpu.runtime.host",
            f"conf={conf_path}",
        ]
        if job.get("batches"):
            cmd.append(f"batches={job['batches']}")
        if job.get("parentTrace"):
            # cross-process trace propagation: the spawned host's batch
            # traces JOIN the control-plane request trace (CLI key=value
            # args merge into the conf dictionary, ConfigManager)
            cmd.append(
                "datax.job.process.telemetry.parenttrace="
                f"{job['parentTrace']}"
            )
        for k, v in (job.get("confOverrides") or {}).items():
            # per-replica conf overrides (same key=value contract): the
            # rescale path passes each replica its state partition
            # assignment (process.state.replicaindex/replicacount)
            cmd.append(f"{k}={v}")
        env = {**os.environ, **self.env}
        stdout = subprocess.DEVNULL
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            stdout = open(os.path.join(self.log_dir, f"{name}.log"), "ab")
        try:
            proc = subprocess.Popen(
                cmd, stdout=stdout, stderr=subprocess.STDOUT, env=env,
                start_new_session=True,
            )
        finally:
            if stdout is not subprocess.DEVNULL:
                stdout.close()  # child keeps its inherited fd
        self._procs[name] = proc
        job["clientId"] = proc.pid
        job["state"] = JobState.Starting
        logger.info("submitted job %s pid=%d conf=%s", name, proc.pid, conf_path)
        return job

    def _proc(self, job: dict) -> Optional[subprocess.Popen]:
        return self._procs.get(job["name"])

    def stop(self, job: dict) -> dict:
        # forget the process so a later get_state doesn't read the
        # SIGTERM exit code as a job failure
        proc = self._procs.pop(job["name"], None)
        pid = job.get("clientId")
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        elif pid:
            # job from a previous service instance: signal by pid
            try:
                os.kill(int(pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        job["state"] = JobState.Idle
        job["clientId"] = None
        return job

    def get_state(self, job: dict) -> str:
        proc = self._proc(job)
        if proc is not None:
            rc = proc.poll()
            if rc is None:
                return JobState.Running
            return JobState.Success if rc == 0 else JobState.Error
        pid = job.get("clientId")
        if pid:
            try:
                os.kill(int(pid), 0)
                return JobState.Running
            except (ProcessLookupError, PermissionError):
                return JobState.Error
        return job.get("state") or JobState.Idle


class K8sJobClient(TpuJobClient):
    """Submits flow jobs as Kubernetes Jobs on a TPU node pool.

    The cluster-submission role Livy/Databricks REST plays for the
    reference (DataX.Config.LivyClient/LivyClient.cs:81-94 submit/poll/
    delete of cluster batches; state mapping per
    InternalService/SparkJobOperation.cs:42-268): render the
    ``deploy/k8s/tpu-job.yaml`` manifest for the flow, POST it to the
    k8s batch API, derive JobState from the Job's status counts, DELETE
    (foreground propagation) to stop.

    Auth follows the in-cluster convention: bearer token from
    ``token``/``token_file`` (defaults to the service-account token
    path). ``http`` is the transport — ``(method, url, body|None) ->
    (status_code, parsed_json)`` — injectable for tests and for custom
    TLS setups.
    """

    TOKEN_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/token"

    def __init__(
        self,
        api_server: str,
        namespace: str = "default",
        token: Optional[str] = None,
        token_file: Optional[str] = None,
        image: str = "dxtpu:latest",
        manifest_path: Optional[str] = None,
        http=None,
        insecure: bool = False,
        accelerator: Optional[str] = None,
        topology: Optional[str] = None,
    ):
        self.api_server = api_server.rstrip("/")
        self.namespace = namespace
        self.image = image
        # TPU placement overrides for the rendered Job (the template's
        # nodeSelector values are the v5e defaults)
        self.accelerator = accelerator
        self.topology = topology
        self.manifest_path = manifest_path or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "deploy", "k8s", "tpu-job.yaml",
        )
        self._token = token
        self._token_file = token_file
        self.insecure = insecure
        self._http = http or self._urllib_http

    # -- transport -------------------------------------------------------
    def _bearer(self) -> Optional[str]:
        if self._token:
            return self._token
        path = self._token_file or self.TOKEN_PATH
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                return f.read().strip()
        return None

    def _urllib_http(self, method: str, url: str, body: Optional[dict]):
        import ssl
        import urllib.error
        import urllib.request

        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        req.add_header("Accept", "application/json")
        tok = self._bearer()
        if tok:
            req.add_header("Authorization", f"Bearer {tok}")
        ctx = ssl._create_unverified_context() if self.insecure else None
        try:
            with urllib.request.urlopen(req, context=ctx, timeout=30) as r:
                return r.status, json.loads(r.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read().decode() or "{}")
            except ValueError:
                payload = {}
            return e.code, payload

    # -- manifest --------------------------------------------------------
    def _k8s_name(self, job: dict) -> str:
        safe = "".join(
            c if c.isalnum() or c == "-" else "-" for c in job["name"].lower()
        ).strip("-")
        return f"dxtpu-job-{safe}"

    @staticmethod
    def _label_safe(value: str) -> str:
        """k8s label-value charset ([A-Za-z0-9._-], alnum ends, <=63) —
        also what makes the raw-text FLOWNAME/JOBNAME substitution safe
        against YAML metacharacters in user-authored flow names."""
        safe = "".join(
            c if c.isalnum() or c in "._-" else "-" for c in value
        )[:63]
        return safe.strip("._-") or "flow"

    def render_manifest(self, job: dict) -> dict:
        """deploy/k8s/tpu-job.yaml with FLOWNAME/JOBNAME substituted —
        the manifest IS the submission payload (no drift between the
        documented shape and what the client sends)."""
        import yaml

        with open(self.manifest_path, encoding="utf-8") as f:
            text = f.read()
        flow = self._label_safe(job.get("flowName") or job["name"])
        text = text.replace("FLOWNAME", flow).replace(
            "JOBNAME", self._label_safe(job["name"])
        )
        manifest = yaml.safe_load(text)
        manifest["metadata"]["name"] = self._k8s_name(job)
        manifest["metadata"].setdefault("labels", {})["job"] = (
            self._label_safe(job["name"])
        )
        pod = manifest["spec"]["template"]["spec"]
        if self.accelerator or self.topology:
            sel = pod.setdefault("nodeSelector", {})
            if self.accelerator:
                sel["cloud.google.com/gke-tpu-accelerator"] = self.accelerator
            if self.topology:
                sel["cloud.google.com/gke-tpu-topology"] = self.topology
        container = pod["containers"][0]
        container["image"] = self.image
        if job.get("confPath"):
            container["args"] = [f"conf={job['confPath']}"]
        # append unconditionally (the template always carries an
        # explicit command, so args never shadow an image CMD): a
        # manifest without args must NOT silently drop the replica's
        # partition assignment — a pod running with the default
        # replicaindex=1/replicacount=1 owns every partition and
        # duplicates the rest of the group's processing
        args = container.setdefault("args", [])
        if job.get("batches"):
            args.append(f"batches={job['batches']}")
        if job.get("parentTrace"):
            # same key=value conf-override contract as the local client
            args.append(
                "datax.job.process.telemetry.parenttrace="
                f"{job['parentTrace']}"
            )
        for k, v in (job.get("confOverrides") or {}).items():
            args.append(f"{k}={v}")
        return manifest

    def _jobs_url(self, name: Optional[str] = None) -> str:
        base = (
            f"{self.api_server}/apis/batch/v1/namespaces/"
            f"{self.namespace}/jobs"
        )
        return f"{base}/{name}" if name else base

    # -- TpuJobClient ----------------------------------------------------
    def submit(self, job: dict) -> dict:
        manifest = self.render_manifest(job)
        status, body = self._http("POST", self._jobs_url(), manifest)
        if status == 409:
            # already exists: delete the finished run, then resubmit
            # (Livy parity: a batch id is single-use; k8s Jobs likewise)
            self._delete(self._k8s_name(job))
            self._wait_gone(self._k8s_name(job))
            status, body = self._http("POST", self._jobs_url(), manifest)
        if status not in (200, 201, 202):
            raise RuntimeError(
                f"k8s job submit failed ({status}): "
                f"{body.get('message', body)}"
            )
        job["clientId"] = self._k8s_name(job)
        job["state"] = JobState.Starting
        logger.info(
            "submitted k8s job %s as %s", job["name"], job["clientId"]
        )
        return job

    def _delete(self, k8s_name: str):
        return self._http(
            "DELETE",
            self._jobs_url(k8s_name),
            {"propagationPolicy": "Foreground"},
        )

    def _wait_gone(self, k8s_name: str, timeout_s: float = 30):
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            status, _ = self._http("GET", self._jobs_url(k8s_name), None)
            if status == 404:
                return
            time.sleep(0.5)

    def stop(self, job: dict) -> dict:
        name = job.get("clientId") or self._k8s_name(job)
        status, body = self._delete(name)
        if status not in (200, 202, 404):
            raise RuntimeError(
                f"k8s job delete failed ({status}): "
                f"{body.get('message', body)}"
            )
        job["state"] = JobState.Idle
        job["clientId"] = None
        return job

    def get_state(self, job: dict) -> str:
        name = job.get("clientId") or self._k8s_name(job)
        status, body = self._http("GET", self._jobs_url(name), None)
        if status == 404:
            return job.get("state") if job.get("state") in (
                JobState.Idle, JobState.Success, JobState.Error
            ) else JobState.Idle
        if status != 200:
            raise RuntimeError(f"k8s job get failed ({status})")
        s = body.get("status", {}) or {}
        # the Job controller's conditions are the authoritative terminal
        # signal: a crash-looping pod under restartPolicy OnFailure may
        # exhaust retries without status.failed ever exceeding
        # backoffLimit, so counting alone never surfaces the failure
        for cond in s.get("conditions") or []:
            if str(cond.get("status")).lower() != "true":
                continue
            if cond.get("type") == "Failed":
                return JobState.Error
            if cond.get("type") == "Complete":
                return JobState.Success
        if s.get("active"):
            return JobState.Running
        if s.get("succeeded"):
            return JobState.Success
        return JobState.Starting  # created/retrying, not yet terminal


def make_job_client(conf: Optional[dict] = None, log_dir: Optional[str] = None):
    """Client factory keyed by conf — the role the reference's client
    factory plays choosing Livy vs Databricks vs local
    (DataX.Config/ConfigGenConfiguration SparkType switch)."""
    conf = conf or {}
    kind = (conf.get("type") or "local").lower()
    if kind == "local":
        return LocalJobClient(log_dir=log_dir, env=conf.get("env"))
    if kind in ("k8s", "kubernetes"):
        return K8sJobClient(
            api_server=conf.get("apiserver")
            or "https://kubernetes.default.svc",
            namespace=conf.get("namespace", "default"),
            token=conf.get("token"),
            token_file=conf.get("tokenfile"),
            image=conf.get("image", "dxtpu:latest"),
            manifest_path=conf.get("manifest"),
            insecure=str(conf.get("insecure", "")).lower() == "true",
            accelerator=conf.get("accelerator"),
            topology=conf.get("topology"),
        )
    raise ValueError(f"unknown job client type {kind!r}")


class JobOperation:
    """Start/stop/restart with bounded retries + state sync.

    reference: SparkJobOperation.cs:42-268 (StartJobWithRetries /
    StopJobWithRetries / RestartJob / SyncJobState / SyncAllJobState).
    """

    def __init__(
        self,
        registry: JobRegistry,
        client: TpuJobClient,
        retries: int = 3,
        retry_interval_s: float = 0.5,
        admission_gate: Optional[FleetAdmissionGate] = None,
        replanner=None,
    ):
        self.registry = registry
        self.client = client
        self.retries = retries
        self.retry_interval_s = retry_interval_s
        # fleet placement: the admission gate rejects an oversubscribing
        # submit BEFORE the client spawns anything; the replanner
        # (serve/scheduler.py) recomputes placement after stop/start so
        # freed capacity is reusable
        self.admission_gate = admission_gate
        self.replanner = replanner

    def _notify_replanner(self) -> None:
        if self.replanner is not None:
            try:
                self.replanner.on_job_event()
            except Exception:  # noqa: BLE001 — re-plan must not fail ops
                logger.exception("placement re-plan failed")

    # -- state sync ------------------------------------------------------
    def sync_job_state(self, job_name: str) -> dict:
        job = self.registry.get(job_name)
        if job is None:
            raise KeyError(f"job '{job_name}' not found")
        state = self.client.get_state(job)
        if state != job.get("state"):
            job["state"] = state
            self.registry.upsert(job)
        return job

    def sync_all(self) -> List[dict]:
        return [self.sync_job_state(j["name"]) for j in self.registry.get_all()]

    # -- lifecycle -------------------------------------------------------
    def start_job(self, job_name: str, batches: Optional[int] = None) -> dict:
        job = self.sync_job_state(job_name)
        if job["state"] in (JobState.Running, JobState.Starting):
            return job  # idempotent start (reference: StartJob short-circuit)
        if batches:
            job["batches"] = batches
        if self.admission_gate is not None:
            # raises FleetAdmissionError (recording the rejection on the
            # registry record) before the client spawns anything
            job = self.admission_gate.admit(job)
        with tracing.span("submit", job=job_name):
            # hand the active trace position (the REST request's span
            # tree, when the control plane traces) to the spawned host:
            # its batch spans then root under this submit
            parent = tracing.format_parent(tracing.capture())
            if parent is not None:
                job["parentTrace"] = parent
            job = self.client.submit(job)
        self.registry.upsert(job)
        self._notify_replanner()
        return job

    def start_job_with_retries(self, job_name: str, **kw) -> dict:
        return self._with_retries(lambda: self.start_job(job_name, **kw))

    def stop_job(self, job_name: str) -> dict:
        job = self.sync_job_state(job_name)
        if job["state"] not in (JobState.Running, JobState.Starting):
            return job
        job = self.client.stop(job)
        self.registry.upsert(job)
        self._notify_replanner()
        return job

    def stop_job_with_retries(self, job_name: str) -> dict:
        return self._with_retries(lambda: self.stop_job(job_name))

    # -- in-place rescale -------------------------------------------------
    def replica_records(self, job_name: str) -> List[dict]:
        """The job's live replica records (``replicaOf`` == job, state
        running/starting — stopped replicas stay in the registry as
        history, like any stopped job), in replica order."""
        out = [
            r for r in self.registry.get_all()
            if r.get("replicaOf") == job_name
            and r.get("state") in (JobState.Running, JobState.Starting)
        ]
        out.sort(key=lambda r: r.get("replicaIndex") or 0)
        return out

    def job_lineage(self, flow: str) -> List[dict]:
        """A flow's replica lineage for the fleet telemetry plane
        (obs/fleetview.py ``lineage_fn``): the base record plus EVERY
        replica record in the registry — stopped replicas included,
        they are the history a cross-replica trace stitches over — in
        (replicaIndex, name) order with the base's authoritative
        ``statePartitionMap`` attached to each entry. Returns [] for
        unknown flows so the fleet view falls back to frame-derived
        lineage."""
        base = None
        replicas = []
        for r in self.registry.get_all():
            if r.get("name") == flow or (
                r.get("flow") == flow and not r.get("replicaOf")
            ):
                base = r
            elif r.get("replicaOf") == flow or (
                r.get("flow") == flow and r.get("replicaOf")
            ):
                replicas.append(r)
        if base is None and not replicas:
            return []
        pmap = (base or {}).get("statePartitionMap") or {}
        replicas.sort(
            key=lambda r: (r.get("replicaIndex") or 0, r.get("name") or "")
        )
        out = []
        for rec in ([base] if base else []) + replicas:
            idx = rec.get("replicaIndex") or 1
            out.append({
                "replica": rec.get("name"),
                "replicaIndex": idx,
                "replicaOf": rec.get("replicaOf"),
                "state": rec.get("state"),
                "statePartitionsOwned": rec.get("statePartitionsOwned"),
                "partitionMap": pmap.get(str(idx)) or pmap.get(idx),
            })
        return out

    def _state_partition_plan(self, base: dict, replicas: int) -> dict:
        """Compute + persist the state-partition map of the new replica
        set: the admitted rescale plan now CARRIES the partition
        assignment (ROADMAP item 4). The map lands on the base job
        record (``statePartitionMap``) and its geometry exports as the
        ``State_Partition_*`` series under DATAX-Fleet; each spawned
        replica receives its contiguous range via conf overrides
        (``process.state.replicaindex``/``replicacount``/``partitions``)
        and pulls exactly those partitions from the snapshot store at
        init — the handoff, not a state loss."""
        from ..runtime.statepartition import (
            DEFAULT_STATE_PARTITIONS,
            partition_map,
            reassigned_partitions,
        )

        partitions = int(
            base.get("statePartitions") or DEFAULT_STATE_PARTITIONS
        )
        old_map = base.get("statePartitionMap") or {}
        new_map = partition_map(replicas, partitions)
        moved = reassigned_partitions(old_map, new_map) if old_map else []
        base["statePartitions"] = partitions
        base["statePartitionMap"] = {
            str(i): parts for i, parts in new_map.items()
        }
        base["statePartitionsReassigned"] = moved
        if self.admission_gate is not None:
            try:
                self.admission_gate.metrics.send_batch_metrics({
                    "State_Partition_Count": float(partitions),
                    "State_Partition_Reassigned_Count": float(len(moved)),
                })
            except Exception:  # noqa: BLE001 — metrics never gate a rescale
                logger.exception("state partition metric export failed")
        return new_map

    @staticmethod
    def _replica_conf_overrides(index: int, count: int,
                                partitions: int) -> Dict[str, str]:
        return {
            "datax.job.process.state.replicaindex": str(index),
            "datax.job.process.state.replicacount": str(count),
            "datax.job.process.state.partitions": str(partitions),
        }

    def _apply_member_assignment(
        self, rec: dict, position: int, count: int, partitions: int,
        pmap: Dict[int, List[int]],
    ) -> dict:
        """Put one PRE-EXISTING group member (the base job at position
        1, surviving replicas after it) onto the new partition map: its
        position's overrides merge into the record and, when the
        effective assignment changed, the member is restarted so the
        running process picks the map up (conf is read at host start).
        Without this the base would keep running replicacount=1 after a
        1->2 scale-up and own EVERY partition alongside the new replica
        — duplicate processing under the key-routed ingest filter and
        both replicas clobbering the same mirror prefixes. The rescale
        only returns once every member runs the same map."""
        from ..runtime.statepartition import DEFAULT_STATE_PARTITIONS

        target = self._replica_conf_overrides(position, count, partitions)
        current = dict(rec.get("confOverrides") or {})
        # what a host with no overrides assumes — a base job started
        # before any rescale carries none, yet already runs this map
        defaults = self._replica_conf_overrides(
            1, 1, DEFAULT_STATE_PARTITIONS
        )
        changed = any(
            current.get(k, defaults[k]) != v for k, v in target.items()
        )
        rec["confOverrides"] = {**current, **target}
        rec["statePartitionsOwned"] = sorted(pmap.get(position, []))
        if changed and rec.get("state") in (
            JobState.Running, JobState.Starting,
        ):
            with tracing.span("rescale/restart", job=rec["name"]):
                rec = self.client.stop(rec)
                self.registry.upsert(rec)
                deadline = time.time() + 10
                while time.time() < deadline and self.client.get_state(
                    rec
                ) in (JobState.Running, JobState.Starting):
                    time.sleep(self.retry_interval_s)
                parent = tracing.format_parent(tracing.capture())
                if parent is not None:
                    rec["parentTrace"] = parent
                rec = self.client.submit(rec)
        self.registry.upsert(rec)
        return rec

    def rescale(self, job_name: str, replicas: int) -> List[dict]:
        """In-place replica scaling — the path a replica-count change
        used to require a stop+start for. ``replicas`` counts the base
        job plus ``<job>-rN`` replica records sharing its conf (a
        competing-consumer group against the same source). Scale-UP is
        vetted by the fleet admission gate BEFORE any process spawns
        (``FleetAdmissionGate.admit_replicas`` — capacity codes over N
        copies of the flow's footprint); scale-DOWN stops the
        highest-numbered replicas first. The admitted plan carries the
        state-partition map (``_state_partition_plan``): EVERY member
        of the new replica set — the base job and surviving replicas
        included, restarted when their assignment changed — runs its
        contiguous partition range as conf overrides, so stateful
        flows hand partitions off instead of losing them (and never
        double-own one). The replanner refreshes placement after every
        change. Returns the live record set (base + replicas)."""
        base = self.sync_job_state(job_name)
        replicas = max(1, int(replicas))
        live = self.replica_records(job_name)
        have = 1 + len(live)
        if replicas > have and self.admission_gate is not None:
            # raises FleetAdmissionError (recording the rejection on
            # the base record) before the client spawns anything AND
            # before the partition plan lands on the record — a
            # rejected scale-up must not persist a map describing a
            # replica set that never materialized
            self.admission_gate.admit_replicas(base, replicas)
        pmap = self._state_partition_plan(base, replicas)
        partitions = int(base["statePartitions"])
        self.registry.upsert(base)
        if replicas < have:
            # stop the highest-numbered replicas first (the base job is
            # never stopped by a rescale — replicas floor at 1), BEFORE
            # survivors re-conf: their orphaned partitions come from
            # the stopped tail, never from a still-running member
            for rec in list(reversed(live))[: have - replicas]:
                rec = self.client.stop(rec)
                self.registry.upsert(rec)
            live = live[: replicas - 1]
        # every pre-existing member adopts the new map before any
        # successor spawns: shrinking ranges first means a partition is
        # at worst transiently unowned, never owned twice
        for position, rec in enumerate([base] + live, start=1):
            self._apply_member_assignment(
                rec, position, replicas, partitions, pmap
            )
        if replicas > have:
            taken = {r.get("replicaIndex") for r in live}
            idx = 2
            for i in range(replicas - have):
                while idx in taken:
                    idx += 1
                taken.add(idx)
                # the i-th new replica takes position have+1+i in the
                # final set — its contiguous partition range under pmap
                position = have + 1 + i
                rec = {
                    "name": f"{job_name}-r{idx}",
                    "flow": base.get("flow"),
                    "confPath": base.get("confPath"),
                    "replicaOf": job_name,
                    "replicaIndex": idx,
                    "state": JobState.Idle,
                    "statePartitionsOwned": sorted(pmap.get(position, [])),
                    "confOverrides": self._replica_conf_overrides(
                        position, replicas, partitions
                    ),
                }
                with tracing.span(
                    "rescale/submit", job=rec["name"], of=job_name
                ):
                    parent = tracing.format_parent(tracing.capture())
                    if parent is not None:
                        rec["parentTrace"] = parent
                    rec = self.client.submit(rec)
                self.registry.upsert(rec)
                live.append(rec)
        self._notify_replanner()
        return [base] + self.replica_records(job_name)

    def restart_job(self, job_name: str, batches: Optional[int] = None) -> dict:
        self.stop_job_with_retries(job_name)
        # wait until the client reports not-running before resubmitting
        deadline = time.time() + 10
        while time.time() < deadline:
            if self.sync_job_state(job_name)["state"] not in (
                JobState.Running, JobState.Starting,
            ):
                break
            time.sleep(self.retry_interval_s)
        return self.start_job_with_retries(job_name, batches=batches)

    def wait_for_state(
        self, job_name: str, states, timeout_s: float = 30
    ) -> dict:
        """Poll sync until the job reaches one of ``states``
        (EnsureJobState semantics, SparkJobOperation.cs:229-266)."""
        deadline = time.time() + timeout_s
        job = self.sync_job_state(job_name)
        while job["state"] not in states and time.time() < deadline:
            time.sleep(self.retry_interval_s)
            job = self.sync_job_state(job_name)
        return job

    def _with_retries(self, fn):
        last: Optional[Exception] = None
        for _ in range(self.retries):
            try:
                return fn()
            except FleetAdmissionError:
                # deterministic rejection: the fleet state that refused
                # the job does not change by retrying
                raise
            except Exception as e:  # noqa: BLE001 — retried, then re-raised
                last = e
                logger.warning("job operation failed, retrying: %s", e)
                time.sleep(self.retry_interval_s)
        raise last  # type: ignore[misc]
