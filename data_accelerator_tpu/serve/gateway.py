"""Gateway: authenticated reverse proxy in front of the flow services.

reference: Services/DataX.Gateway/DataX.Gateway.Api/Controllers/
GatewayController.cs — a single controller that (a) authenticates the
caller via AAD, (b) checks membership in the reader/writer roles and an
optional client whitelist (:113-148), then (c) forwards
``api/{service}/{*path}`` through the Service Fabric reverse proxy to
the internal service, attaching the caller's resolved roles as request
headers the services trust (:178-208).

TPU-native stand-in: bearer-token auth from a local auth table (the
AAD-role-assignment analog; tokens map to user + roles and can live in
the secret vault), per-method role enforcement (GET needs reader,
POST needs writer), and plain HTTP forwarding to registered backend
base-URLs. Caller-supplied ``X-DataX-*`` headers are stripped — only
the gateway mints them, which is exactly why services can trust them.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

ROLE_READER = "DataXReader"
ROLE_WRITER = "DataXWriter"

logger = logging.getLogger(__name__)


class AuthTable:
    """token -> (user, roles). The AAD role-assignment analog."""

    def __init__(self, entries: Optional[Dict[str, Tuple[str, List[str]]]] = None):
        self._entries = dict(entries or {})

    @staticmethod
    def from_file(path: str) -> "AuthTable":
        """JSON file: {"<token>": {"user": "...", "roles": [...]}, ...}"""
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
        return AuthTable({
            tok: (v.get("user", ""), list(v.get("roles") or []))
            for tok, v in raw.items()
        })

    def add(self, token: str, user: str, roles: List[str]) -> None:
        self._entries[token] = (user, roles)

    def resolve(self, token: Optional[str]) -> Optional[Tuple[str, List[str]]]:
        if not token:
            return None
        return self._entries.get(token)


class Gateway:
    """Role-checked reverse proxy over registered backend services."""

    def __init__(
        self,
        auth: AuthTable,
        backends: Dict[str, str],
        host: str = "127.0.0.1",
        port: int = 0,
        whitelist: Optional[List[str]] = None,
        timeout_s: float = 30.0,
    ):
        self.auth = auth
        self.backends = dict(backends)  # service name -> base url
        self.whitelist = list(whitelist or [])
        self.timeout_s = timeout_s
        gw = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                logger.debug("gateway %s", fmt % args)

            def _respond(self, status: int, payload: dict) -> None:
                data = json.dumps(payload, default=str).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _forward(self, method: str) -> None:
                status, payload = gw.handle(
                    method,
                    self.path,
                    dict(self.headers),
                    self.rfile.read(
                        int(self.headers.get("Content-Length") or 0)
                    ) or None,
                )
                self._respond(status, payload)

            def do_GET(self):
                self._forward("GET")

            def do_POST(self):
                self._forward("POST")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- core -------------------------------------------------------------
    def authenticate(self, headers: Dict[str, str]) -> Optional[Tuple[str, List[str]]]:
        authz = headers.get("Authorization") or headers.get("authorization") or ""
        token = authz[7:].strip() if authz.lower().startswith("bearer ") else authz
        return self.auth.resolve(token.strip() or None)

    def authorize(
        self, method: str, user: str, roles: List[str]
    ) -> Optional[str]:
        """Returns an error message, or None when allowed
        (GatewayController.cs:113-148 role + whitelist check)."""
        if self.whitelist and user not in self.whitelist:
            return f"user '{user}' is not whitelisted"
        if method == "GET":
            if ROLE_READER not in roles and ROLE_WRITER not in roles:
                return "reader role required"
        else:
            if ROLE_WRITER not in roles:
                return "writer role required"
        return None

    def handle(
        self,
        method: str,
        path: str,
        headers: Dict[str, str],
        body: Optional[bytes],
    ) -> Tuple[int, dict]:
        ident = self.authenticate(headers)
        if ident is None:
            return 401, {"error": {"message": "authentication required"}}
        user, roles = ident
        err = self.authorize(method, user, roles)
        if err:
            return 403, {"error": {"message": err}}

        # api/{service}/{*path} -> backend base url + api/{*path}
        parts = path.lstrip("/").split("/", 2)
        if len(parts) < 2 or parts[0] != "api":
            return 404, {"error": {"message": "expected /api/{service}/..."}}
        service = parts[1]
        rest = parts[2] if len(parts) > 2 else ""
        base = self.backends.get(service)
        if base is None:
            return 404, {"error": {"message": f"unknown service '{service}'"}}
        url = f"{base.rstrip('/')}/api/{rest}"

        fwd_headers = {
            k: v
            for k, v in headers.items()
            if not k.lower().startswith("x-datax-")
            and k.lower() not in ("host", "content-length", "authorization")
        }
        # only the gateway mints identity headers (:178-208)
        fwd_headers["X-DataX-User"] = user
        fwd_headers["X-DataX-Roles"] = ",".join(roles)
        req = urllib.request.Request(
            url, data=body, headers=fwd_headers, method=method
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                raw = resp.read() or b"{}"
                try:
                    return resp.status, json.loads(raw)
                except ValueError:
                    return resp.status, {"raw": raw.decode("utf-8", "replace")}
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read() or b"{}")
            except ValueError:
                return e.code, {"error": {"message": str(e)}}
        except (urllib.error.URLError, OSError) as e:
            return 502, {"error": {"message": f"backend unreachable: {e}"}}

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        logger.info("gateway listening on :%d", self.port)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
