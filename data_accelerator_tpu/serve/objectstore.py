"""Shared object store: the remote design/runtime storage backend.

reference: the reference keeps design-time docs in CosmosDB and runtime
artifacts in blob storage behind storage interfaces
(DataX.Config/Storage/{IDesignTimeConfigStorage,IRuntimeConfigStorage}.cs),
so the control plane and every cluster worker see one config source.
Here the same role is played by any HTTP object store speaking a
minimal S3-flavored REST subset:

    PUT    /<bucket>/<key>          store bytes
    GET    /<bucket>/<key>          fetch bytes (404 when absent)
    DELETE /<bucket>/<key>          remove
    GET    /<bucket>?prefix=<p>     JSON list of keys

``ObjectStoreClient`` is the tiny dependency-free client (urllib, token
auth, injectable transport for tests); ``ObjectStoreServer`` is a
bundled implementation of the same protocol (threaded http.server over
a local directory) so one-box and CI runs get a real shared store
without any cloud dependency — workers on other hosts point at its URL.
Engine processes resolve ``objstore://host:port/bucket/key`` conf URLs
through this client (core/confmanager.py), which is what lets a job
submitted to a cluster read the configs the control plane generated.
"""

from __future__ import annotations

import json
import logging
import os
import random
import re
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

Transport = Callable[[str, str, Optional[bytes]], Tuple[int, bytes]]

# bounded exponential backoff on transient store failures: 3 attempts,
# doubling delay with jitter. Every consumer shares the SAME retry
# shape; what differs is the posture AFTER the retries are spent —
# fail-open for the compile cache (compile/aotcache.py: a cold compile
# beats a dead host), fail-closed for state snapshots
# (runtime/statepartition.py: losing state is never better than
# failing the batch).
RETRY_ATTEMPTS = 3
RETRY_BASE_S = 0.05
RETRY_MAX_S = 1.0


def retry_transient(fn, attempts: int = RETRY_ATTEMPTS,
                    base_s: float = RETRY_BASE_S,
                    max_s: float = RETRY_MAX_S,
                    what: str = "object-store operation"):
    """Run ``fn`` with bounded, jittered exponential backoff on
    transient failures (transport errors and 5xx responses surface as
    IOError/OSError from the client methods below). The LAST failure
    re-raises — posture (open/closed) is the caller's decision."""
    last: Optional[Exception] = None
    for attempt in range(max(1, int(attempts))):
        try:
            return fn()
        except (IOError, OSError) as e:  # includes urllib.error.URLError
            last = e
            if attempt + 1 >= attempts:
                break
            delay = min(max_s, base_s * (2 ** attempt))
            delay *= 0.5 + random.random()  # jitter: 0.5x..1.5x
            logger.warning(
                "%s failed (attempt %d/%d, retrying in %.0f ms): %s",
                what, attempt + 1, attempts, delay * 1000, e,
            )
            time.sleep(delay)
    raise last  # type: ignore[misc]


class ObjectStoreClient:
    """Minimal object-store client over the REST subset above.

    Transient failures — connection errors and 5xx responses — retry
    with bounded jittered backoff (``retries`` attempts); definitive
    answers (2xx, 404, 4xx) never retry."""

    def __init__(
        self,
        endpoint: str,
        bucket: str = "dxtpu",
        token: Optional[str] = None,
        http: Optional[Transport] = None,
        retries: int = RETRY_ATTEMPTS,
    ):
        self.endpoint = endpoint.rstrip("/")
        parsed = urllib.parse.urlparse(self.endpoint)
        if parsed.scheme not in ("http", "https"):
            raise ValueError(
                f"object-store endpoint must be http(s)://, got {endpoint!r}"
            )
        if parsed.path:
            # objstore:// URLs partition as host/bucket/key; a path
            # component would be swallowed as the bucket — fail loud
            raise ValueError(
                "object-store endpoint must not carry a path component "
                f"(got {endpoint!r}); buckets name the top level"
            )
        self.bucket = bucket
        self.token = token
        self.retries = max(1, int(retries))
        self._http = http or self._urllib_http

    def _request(self, method: str, url: str, body: Optional[bytes],
                 what: str) -> Tuple[int, bytes]:
        """One logical request: transport errors and 5xx answers are
        transient (the server may be restarting, the LB draining) and
        retry with jittered backoff; anything else is definitive."""

        def once():
            status, resp = self._http(method, url, body)
            if status >= 500:
                raise IOError(f"{what} failed ({status})")
            return status, resp

        return retry_transient(once, attempts=self.retries, what=what)

    # -- transport -------------------------------------------------------
    def _urllib_http(self, method: str, url: str, body: Optional[bytes]):
        req = urllib.request.Request(url, data=body, method=method)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def _url(self, key: str = "", query: str = "") -> str:
        path = f"{self.endpoint}/{self.bucket}"
        if key:
            path += "/" + urllib.parse.quote(key)
        if query:
            path += "?" + query
        return path

    # -- operations ------------------------------------------------------
    def put(self, key: str, content: bytes) -> None:
        status, body = self._request(
            "PUT", self._url(key), content, f"object put {key!r}"
        )
        if status not in (200, 201, 204):
            raise IOError(f"object put {key!r} failed ({status})")

    def get(self, key: str) -> Optional[bytes]:
        status, body = self._request(
            "GET", self._url(key), None, f"object get {key!r}"
        )
        if status == 404:
            return None
        if status != 200:
            raise IOError(f"object get {key!r} failed ({status})")
        return body

    def delete(self, key: str) -> bool:
        status, _ = self._request(
            "DELETE", self._url(key), None, f"object delete {key!r}"
        )
        if status in (200, 202, 204):
            return True
        if status == 404:
            return False
        raise IOError(f"object delete {key!r} failed ({status})")

    def list(self, prefix: str = "") -> List[str]:
        q = "prefix=" + urllib.parse.quote(prefix) if prefix else ""
        status, body = self._request(
            "GET", self._url(query=q), None, f"object list {prefix!r}"
        )
        if status != 200:
            raise IOError(f"object list {prefix!r} failed ({status})")
        return json.loads(body.decode() or "[]")

    def delete_prefix(self, prefix: str) -> int:
        n = 0
        for key in self.list(prefix):
            if self.delete(key):
                n += 1
        return n

    def url_for(self, key: str) -> str:
        """objstore:// URL a worker can resolve back through this
        protocol (utils/fs.read_text -> fetch_objstore_url). TLS
        endpoints keep their scheme via the objstore+https:// form."""
        scheme, host = self.endpoint.split("://", 1)
        prefix = "objstore+https" if scheme == "https" else "objstore"
        return f"{prefix}://{host}/{self.bucket}/{key}"


_SAFE_KEY_RE = re.compile(r"^[\w\-./ %]+$")


class _StoreHandler(BaseHTTPRequestHandler):
    server_version = "dxtpu-objectstore/1"

    def log_message(self, fmt, *args):  # quiet; logger instead
        logger.debug("objectstore: " + fmt, *args)

    # path: /<bucket>/<key...> — bucket is one segment
    def _parse(self):
        parsed = urllib.parse.urlparse(self.path)
        parts = parsed.path.lstrip("/").split("/", 1)
        bucket = urllib.parse.unquote(parts[0]) if parts[0] else ""
        key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
        query = urllib.parse.parse_qs(parsed.query)
        return bucket, key, query

    def _check_auth(self) -> bool:
        token = self.server.token  # type: ignore[attr-defined]
        if not token:
            return True
        got = self.headers.get("Authorization", "")
        if got == f"Bearer {token}":
            return True
        self._send(401, b"unauthorized")
        return False

    def _send(self, status: int, body: bytes = b"",
              ctype: str = "application/octet-stream"):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def do_PUT(self):
        if not self._check_auth():
            return
        bucket, key, _ = self._parse()
        if not bucket or not key or not _SAFE_KEY_RE.match(key) \
                or ".." in key:
            self._send(400, b"bad key")
            return
        n = int(self.headers.get("Content-Length", 0))
        data = self.rfile.read(n)
        self.server.store_put(bucket, key, data)  # type: ignore[attr-defined]
        self._send(201)

    def do_GET(self):
        if not self._check_auth():
            return
        bucket, key, query = self._parse()
        if key:
            data = self.server.store_get(bucket, key)  # type: ignore[attr-defined]
            if data is None:
                self._send(404, b"not found")
            else:
                self._send(200, data)
            return
        prefix = (query.get("prefix") or [""])[0]
        keys = self.server.store_list(bucket, prefix)  # type: ignore[attr-defined]
        self._send(200, json.dumps(keys).encode(), "application/json")

    def do_DELETE(self):
        if not self._check_auth():
            return
        bucket, key, _ = self._parse()
        ok = self.server.store_delete(bucket, key)  # type: ignore[attr-defined]
        self._send(204 if ok else 404)


class ObjectStoreServer(ThreadingHTTPServer):
    """Bundled store: the protocol above over a local directory (or
    memory), so a one-box deployment has a real shared config store the
    moment it starts — no cloud account needed. Keys map to files under
    ``root/<bucket>/<key>`` with atomic replace writes."""

    daemon_threads = True

    def __init__(self, port: int = 0, root: Optional[str] = None,
                 token: Optional[str] = None, host: str = "127.0.0.1",
                 advertise: Optional[str] = None):
        """``host``: bind address (0.0.0.0 to serve other hosts).
        ``advertise``: the endpoint URL baked into objstore:// conf
        references — REQUIRED to be externally reachable when workers
        run on other machines; defaults to the bind address."""
        super().__init__((host, port), _StoreHandler)
        self.root = root
        self.token = token
        self.advertise = advertise
        self._bind_host = host
        self._mem: Dict[Tuple[str, str], bytes] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.server_address[1]

    @property
    def endpoint(self) -> str:
        if self.advertise:
            return self.advertise.rstrip("/")
        host = self._bind_host if self._bind_host not in ("", "0.0.0.0") \
            else "127.0.0.1"
        return f"http://{host}:{self.port}"

    def start(self) -> "ObjectStoreServer":
        self._thread = threading.Thread(target=self.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.shutdown()

    # -- backend ---------------------------------------------------------
    def _file(self, bucket: str, key: str) -> str:
        path = os.path.realpath(os.path.join(self.root, bucket, key))
        root = os.path.realpath(self.root)
        if not path.startswith(root + os.sep):
            raise ValueError("key escapes store root")
        return path

    def store_put(self, bucket: str, key: str, data: bytes) -> None:
        if self.root is None:
            with self._lock:
                self._mem[(bucket, key)] = data
            return
        path = self._file(bucket, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def store_get(self, bucket: str, key: str) -> Optional[bytes]:
        if self.root is None:
            with self._lock:
                return self._mem.get((bucket, key))
        try:
            with open(self._file(bucket, key), "rb") as f:
                return f.read()
        except (FileNotFoundError, ValueError, NotADirectoryError,
                IsADirectoryError):
            return None

    def store_delete(self, bucket: str, key: str) -> bool:
        if self.root is None:
            with self._lock:
                return self._mem.pop((bucket, key), None) is not None
        try:
            os.remove(self._file(bucket, key))
            return True
        except (FileNotFoundError, ValueError, NotADirectoryError,
                IsADirectoryError):
            # a directory is not an object; only exact keys delete here
            return False

    def store_list(self, bucket: str, prefix: str) -> List[str]:
        if self.root is None:
            with self._lock:
                return sorted(
                    k for (b, k) in self._mem if b == bucket
                    and k.startswith(prefix)
                )
        base = os.path.join(self.root, bucket)
        out: List[str] = []
        for dirpath, _dirs, files in os.walk(base):
            for fn in files:
                if fn.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), base)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)


def is_objstore_url(path: str) -> bool:
    return path.startswith("objstore://") or path.startswith(
        "objstore+https://"
    )


def fetch_objstore_url(url: str, token: Optional[str] = None) -> str:
    """Resolve an ``objstore://host:port/bucket/key`` (or
    ``objstore+https://``) URL to text — how engine workers read
    configs the control plane stored remotely."""
    if url.startswith("objstore+https://"):
        scheme, rest = "https", url[len("objstore+https://"):]
    elif url.startswith("objstore://"):
        scheme, rest = "http", url[len("objstore://"):]
    else:
        raise ValueError(f"not an objstore URL: {url!r}")
    host, _, bucket_key = rest.partition("/")
    bucket, _, key = bucket_key.partition("/")
    client = ObjectStoreClient(f"{scheme}://{host}", bucket, token=token)
    data = client.get(key)
    if data is None:
        raise FileNotFoundError(url)
    return data.decode("utf-8")
