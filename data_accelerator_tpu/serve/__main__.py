"""Run the control plane (and optionally the full one-box stack):
``python -m data_accelerator_tpu.serve``.

Args (key=value):
  port=5000          control-plane REST port
  root=/tmp/dxtpu-serve   storage root
  roles=false        require X-DataX-Roles on mutating routes
  web=0              website port (0 = no website)
  gateway=0          gateway port (0 = no gateway; website then talks
                     to the API in-process, the one-box wiring)
  authfile=          gateway auth table JSON (token -> user/roles)
  ingest=0           metrics-ingestor TCP port (0 = off)
  scheduler=0        batch scheduler tick seconds (0 = off)
  tracefile=<root>/telemetry.jsonl
                     control-plane flight recorder; REST requests become
                     rest/<route> traces and spawned jobs join them
                     (datax.job.process.telemetry.parenttrace), so
                     `obs trace` renders one tree from the submit to its
                     batch spans. tracefile=off disables.
  objectstore=       design/runtime configs in a shared object store:
                     an endpoint URL (http://host:port) to use an
                     external store, or serve:<port> to also run the
                     bundled store server here (workers point at it)
  objectstore.host=  bundled store bind address (0.0.0.0 for remote
                     workers; default 127.0.0.1)
  objectstore.advertise=  endpoint URL baked into generated objstore://
                     conf references (must be reachable from workers)
  jobclient=local    job submission: local (child processes) or k8s
  fleetspec=         fleet-spec JSON for the DX4xx admission gate
                     (chips, hbmPerChipBytes, ... — see ANALYSIS.md
                     "Placement model"); default 8 x 16 GiB
  admission=true     false = skip the fleet admission gate on job
                     submits (the reference's blind-deploy behavior)
  k8s.apiserver=     k8s API server URL (default in-cluster)
  k8s.namespace=     k8s namespace (default "default")
  k8s.image=         engine image for rendered TPU Jobs
  k8s.tokenfile=     bearer-token file (default service-account path)

The one-box analog of the reference's local container entry
(DeploymentLocal/finalrun.sh): flow services + gateway + website +
metrics path in one process, local file storage under ``root``.
"""

import logging
import sys

from .flowservice import FlowOperation
from .restapi import DataXApi, DataXApiService
from .storage import LocalDesignTimeStorage, LocalRuntimeStorage


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    log = logging.getLogger(__name__)
    args = dict(
        a.split("=", 1) for a in (argv or sys.argv[1:]) if "=" in a
    )
    root = args.get("root", "/tmp/dxtpu-serve")
    port = int(args.get("port", "5000"))
    web_port = int(args.get("web", "0") or 0)
    env_tokens = {}
    # end-to-end trace propagation: the control plane records REST
    # request spans into a flight recorder, generated confs point jobs
    # at the SAME file, and each submit hands its trace position to the
    # spawned host — one `obs trace` tree from designer click to batch
    tracefile = args.get("tracefile", f"{root}/telemetry.jsonl")
    tracer = None
    if tracefile and tracefile != "off":
        from ..obs.telemetry import JsonlWriter, LogWriter, TelemetryLogger
        from ..obs.tracing import Tracer

        tracer = Tracer(TelemetryLogger(
            "DataX-ControlPlane", [LogWriter(), JsonlWriter(tracefile)]
        ))
        env_tokens["telemetryTraceFile"] = tracefile
        log.info("control-plane flight recorder: %s", tracefile)
    if web_port:
        # jobs POST metrics to the website in one-box mode
        # (the localMetricsHttpEndpoint wiring, DeploymentLocal samples)
        env_tokens["localMetricsHttpEndpoint"] = (
            f"http://127.0.0.1:{web_port}/metrics/post"
        )
    parts_pre = []
    objstore = args.get("objectstore")
    if objstore:
        from .objectstore import ObjectStoreClient, ObjectStoreServer
        from .storage import ObjectDesignTimeStorage, ObjectRuntimeStorage

        if objstore.startswith("serve:"):
            store = ObjectStoreServer(
                port=int(objstore.split(":", 1)[1] or 0),
                root=f"{root}/objectstore",
                # workers on other hosts need a reachable bind+advertise
                # (objectstore.host=0.0.0.0 objectstore.advertise=http://<ip>:<port>)
                host=args.get("objectstore.host", "127.0.0.1"),
                advertise=args.get("objectstore.advertise"),
            ).start()
            parts_pre.append(store)
            endpoint = store.endpoint
            log.info("bundled object store on %s", endpoint)
        else:
            endpoint = objstore
        client = ObjectStoreClient(endpoint)
        design_storage = ObjectDesignTimeStorage(client)
        runtime_storage = ObjectRuntimeStorage(
            client, scratch_dir=f"{root}/scratch"
        )
        # fleet telemetry plane: jobs publish windowed frames into the
        # same store; the control plane aggregates them (FleetView)
        # behind GET /fleet/metrics and the website's /metrics rollup
        from ..obs.fleetview import FleetView

        fleet_view = FleetView(client=ObjectStoreClient(endpoint))
        env_tokens["fleetPublishUrl"] = (
            f"objstore://{endpoint.split('://', 1)[-1]}/dxtpu"
        )
        log.info("fleet telemetry plane: frames -> %s",
                 env_tokens["fleetPublishUrl"])
    else:
        design_storage = LocalDesignTimeStorage(f"{root}/design")
        runtime_storage = LocalRuntimeStorage(f"{root}/runtime")
        fleet_view = None

    job_client = None
    if args.get("jobclient", "local") != "local":
        from .jobs import make_job_client

        job_client = make_job_client(
            {"type": args["jobclient"],
             **{k[4:]: v for k, v in args.items() if k.startswith("k8s.")}},
        )

    fleet_spec = None
    if args.get("fleetspec"):
        from ..analysis import load_fleet_spec

        fleet_spec = load_fleet_spec(args["fleetspec"])
        log.info("fleet spec: %s", fleet_spec.to_dict())

    flow_ops = FlowOperation(
        design_storage,
        runtime_storage,
        job_client=job_client,
        env_tokens=env_tokens,
        fleet_spec=fleet_spec,
        fleet_admission=args.get("admission", "true") != "false",
    )
    # LiveQuery serving plane: the real server runs the deadline-tick
    # dispatcher thread so concurrent tenants' executes micro-batch
    # (lq.* args override the datax.job.process.lq.* defaults, e.g.
    # lq.maxbatchwaitms=8 lq.tenant.maxqps=50; lq.ticker=false falls
    # back to the tickless in-process mode)
    import os as _os

    from ..compile.aotcache import compile_conf_for
    from ..lq.service import LiveQueryService

    lq_conf = {
        f"datax.job.process.lq.{k[3:]}": v
        for k, v in args.items() if k.startswith("lq.")
    }
    lq_conf.setdefault("datax.job.process.lq.ticker", "true")
    livequery = LiveQueryService(
        conf=lq_conf,
        compile_conf=compile_conf_for(_os.path.join(
            runtime_storage.resolve("livequery"), "compilecache"
        )),
    )
    if fleet_view is not None:
        # job-registry records carry the authoritative partition map;
        # trace lineage stitching prefers them over frame ordering
        fleet_view.lineage_fn = flow_ops.jobs.job_lineage
    api = DataXApi(
        flow_ops, require_roles=args.get("roles", "false") == "true",
        tracer=tracer, livequery=livequery, fleet=fleet_view,
    )
    service = DataXApiService(api, port=port)
    service.start()
    log.info("control plane on :%d (storage %s)", service.port, root)

    parts = parts_pre + [service]
    if int(args.get("ingest", "0") or 0):
        from ..obs.ingestor import MetricsIngestor

        ing = MetricsIngestor(port=int(args["ingest"]))
        parts.append(ing)
        log.info("metrics ingestor on :%d", ing.port)
    gateway = None
    if int(args.get("gateway", "0") or 0):
        from .gateway import AuthTable, Gateway

        auth = (
            AuthTable.from_file(args["authfile"])
            if args.get("authfile")
            else AuthTable()
        )
        gateway = Gateway(
            auth,
            backends={
                "flow": f"http://127.0.0.1:{service.port}",
                "interactivequery": f"http://127.0.0.1:{service.port}",
                "schemainference": f"http://127.0.0.1:{service.port}",
                "livedata": f"http://127.0.0.1:{service.port}",
            },
            port=int(args["gateway"]),
        )
        gateway.start()
        parts.append(gateway)
        log.info("gateway on :%d", gateway.port)
    if web_port:
        from ..web import WebsiteServer

        if gateway is not None:
            # browser traffic must pass the gateway's role gate
            web = WebsiteServer(
                gateway_url=f"http://127.0.0.1:{gateway.port}",
                gateway_token=args.get("webtoken"),
                port=web_port,
            )
            if not args.get("webtoken"):
                log.warning("gateway enabled but no webtoken= given; "
                            "website API calls will be unauthenticated")
        else:
            web = WebsiteServer(api=api, port=web_port, fleet=fleet_view)
        web.start()
        parts.append(web)
        log.info("website on :%d", web.port)
    if float(args.get("scheduler", "0") or 0):
        from .scheduler import TimedScheduler

        sched = TimedScheduler(
            flow_ops,
            interval_s=float(args["scheduler"]),
            replanner=flow_ops.placement,
            fleet_view=fleet_view,
        )
        sched.start()
        parts.append(sched)
        log.info("batch scheduler every %ss", sched.interval_s)

    try:
        # the API service already runs on its own thread; park here
        import threading

        threading.Event().wait()
    except KeyboardInterrupt:
        for p in parts:
            try:
                getattr(p, "stop", getattr(p, "close", lambda: None))()
            except Exception:  # noqa: BLE001 — best-effort shutdown
                pass


if __name__ == "__main__":
    main()
