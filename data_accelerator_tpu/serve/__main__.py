"""Run the control-plane service: ``python -m data_accelerator_tpu.serve``.

Args (key=value): port=5000 root=/tmp/dxtpu-serve roles=false

The one-box analog of the reference's Flow.ManagementService container
entry (DeploymentLocal/finalrun.sh): all four flow services + gateway
role gate in one process, local file storage under ``root``.
"""

import logging
import sys

from .flowservice import FlowOperation
from .restapi import DataXApi, DataXApiService
from .storage import LocalDesignTimeStorage, LocalRuntimeStorage


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    args = dict(
        a.split("=", 1) for a in (argv or sys.argv[1:]) if "=" in a
    )
    root = args.get("root", "/tmp/dxtpu-serve")
    port = int(args.get("port", "5000"))
    flow_ops = FlowOperation(
        LocalDesignTimeStorage(f"{root}/design"),
        LocalRuntimeStorage(f"{root}/runtime"),
    )
    api = DataXApi(
        flow_ops, require_roles=args.get("roles", "false") == "true"
    )
    service = DataXApiService(api, port=port)
    logging.getLogger(__name__).info(
        "control plane on :%d (storage %s)", service.port, root
    )
    try:
        service.serve_forever()
    except KeyboardInterrupt:
        service.stop()


if __name__ == "__main__":
    main()
