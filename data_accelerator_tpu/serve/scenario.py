"""Scenario runner: ordered steps over a live API, optionally N-way
parallel.

reference: Tests/ScenarioTester — ``[Step]``-attributed methods share a
``ScenarioContext`` and run in declaration order
(ScenarioTester/ScenarioTester/StepAttribute.cs, ScenarioDescription);
the runner executes a scenario N times in parallel and reports per-step
pass/fail. Used both by the e2e test suite (Tests/DataXScenarios) and
the production liveness prober (Services/JobRunner) — same split here:
tests and obs/jobrunner both drive this runner.
"""

from __future__ import annotations

import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


class ScenarioContext(dict):
    """Shared state across a scenario's steps (ScenarioContext analog)."""


@dataclass
class StepResult:
    name: str
    success: bool
    elapsed_s: float
    error: Optional[str] = None


@dataclass
class ScenarioResult:
    name: str
    steps: List[StepResult] = field(default_factory=list)

    @property
    def success(self) -> bool:
        return all(s.success for s in self.steps)

    @property
    def failed_step(self) -> Optional[str]:
        for s in self.steps:
            if not s.success:
                return s.name
        return None


@dataclass
class Scenario:
    """Named ordered steps; each step is ``fn(ctx) -> None`` and may
    read/write the shared context."""

    name: str
    steps: List[Callable] = field(default_factory=list)

    def step(self, fn: Callable) -> Callable:
        """Decorator registering a step in declaration order."""
        self.steps.append(fn)
        return fn

    def run(self, ctx: Optional[ScenarioContext] = None) -> ScenarioResult:
        """Run steps in order; a failing step aborts the rest
        (fail-fast like the reference runner)."""
        ctx = ctx if ctx is not None else ScenarioContext()
        result = ScenarioResult(self.name)
        for fn in self.steps:
            t0 = time.time()
            try:
                fn(ctx)
                result.steps.append(
                    StepResult(fn.__name__, True, time.time() - t0)
                )
            except Exception:  # noqa: BLE001 — recorded per step
                result.steps.append(StepResult(
                    fn.__name__, False, time.time() - t0,
                    error=traceback.format_exc(limit=5),
                ))
                break
        return result

    def run_parallel(
        self, n: int, make_ctx: Optional[Callable[[int], ScenarioContext]] = None
    ) -> List[ScenarioResult]:
        """N concurrent executions (the runner's parallel mode)."""
        results: Dict[int, ScenarioResult] = {}
        lock = threading.Lock()

        def run_one(i: int) -> None:
            ctx = make_ctx(i) if make_ctx else ScenarioContext({"execution": i})
            r = self.run(ctx)
            with lock:
                results[i] = r

        threads = [
            threading.Thread(target=run_one, args=(i,)) for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return [results[i] for i in range(n)]
