"""Runtime config generation: flow document -> runnable flat ``.conf``.

reference: DataX.Config/PublicService/RuntimeConfigGeneration.cs:21-110
and the ordered IFlowDeploymentProcessor chain
(ConfigGeneration/Processor/S100_RestoreFlowConfig.cs ...
S900_FinishUp.cs). Stage numbering and responsibilities preserved:

  S100 restore/port flow defaults      S550 batch inputs
  S200 merge job template defaults     S600 per-job config resolution
  S300 validate gui                    S650 flatten JSON -> .conf
  S400 prepare job tokens              S700 write runtime files
  S450 generate transform (codegen)    S800 upsert job records
  S500 resolve outputs/windows/state   S850 metrics config
                                       S900 finalize + save flow doc

The TPU flavor: job tokens describe chips/batch capacity instead of
executors/memory, and generated confs run directly on the local
StreamingHost (runtime/host.py) — the reference's spark-submit target
is replaced by the engine process itself.
"""

from __future__ import annotations

import copy
import json
import logging
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..compile.codegen import CodegenEngine, RulesCode
from ..compile.flattener import ConfigFlattener
from ..compile.flattener_schema import DEFAULT_FLATTENER_SCHEMA
from .flowbuilder import FlowConfigBuilder, RuleDefinitionGenerator, _deep_merge
from .storage import DesignTimeStorage, JobRegistry, LocalRuntimeStorage
from .templating import TokenDictionary, unresolved_tokens

logger = logging.getLogger(__name__)


@dataclass
class GenerationResult:
    flow_name: str
    job_names: List[str] = field(default_factory=list)
    conf_paths: List[str] = field(default_factory=list)
    files: Dict[str, str] = field(default_factory=dict)
    metrics: dict = field(default_factory=dict)
    errors: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.errors


class RuntimeConfigGeneration:
    """Run the S100–S900 chain for one flow."""

    def __init__(
        self,
        design_storage: DesignTimeStorage,
        runtime_storage: LocalRuntimeStorage,
        codegen: Optional[CodegenEngine] = None,
        env_tokens: Optional[Dict[str, str]] = None,
    ):
        self.design = design_storage
        self.runtime = runtime_storage
        self.codegen = codegen or CodegenEngine()
        self.jobs = JobRegistry(runtime_storage)
        self.rule_gen = RuleDefinitionGenerator()
        # environment-level token defaults (EngineEnvironment analog,
        # DataX.Flow.Common/EngineEnvironment.cs:26-237) — e.g. the
        # one-box website metrics endpoint; flow-level values win
        self.env_tokens = dict(env_tokens or {})

    # -- public entry ----------------------------------------------------
    def generate(self, flow_name: str) -> GenerationResult:
        doc = self.design.get_by_name(flow_name)
        if doc is None:
            return GenerationResult(flow_name, errors=[f"flow '{flow_name}' not found"])
        result = GenerationResult(flow_name)
        ctx: Dict[str, Any] = {"doc": doc, "result": result}
        for stage in (
            self._s100_restore,
            self._s200_merge_defaults,
            self._s300_validate,
            self._s400_job_tokens,
            self._s450_transform,
            self._s500_resolve,
            self._s550_batch,
            self._s600_job_configs,
            self._s620_conformance,
            self._s630_compile,
            self._s640_pilot,
            self._s660_mesh,
            self._s650_flatten,
            self._s700_write_files,
            self._s800_jobs,
            self._s850_metrics,
            self._s900_finalize,
        ):
            try:
                stage(ctx)
            except Exception as e:  # noqa: BLE001 — surfaced per stage
                logger.exception("generation stage %s failed", stage.__name__)
                result.errors.append(f"{stage.__name__}: {e}")
                return result
        return result

    # -- stages ----------------------------------------------------------
    def _s100_restore(self, ctx) -> None:
        """Ensure structural defaults exist (S100_RestoreFlowConfig)."""
        doc = ctx["doc"]
        if "gui" not in doc:
            # gui-only save: wrap it
            ctx["doc"] = FlowConfigBuilder().build(doc)
            return
        ctx["doc"] = FlowConfigBuilder().build(doc["gui"], existing=doc)

    def _s200_merge_defaults(self, ctx) -> None:
        """Merge job-template defaults (S200: defaultSparkJobTemplate).
        Per-job entries inherit jobCommonTokens."""
        cp = ctx["doc"]["commonProcessor"]
        cp.setdefault("jobs", [{"partitionJobNumber": "1"}])
        ctx["job_common"] = dict(cp.get("jobCommonTokens") or {})

    _NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]*$")

    def _s300_validate(self, ctx) -> None:
        doc = ctx["doc"]
        gui = doc["gui"]
        if not doc.get("name"):
            raise ValueError("flow has no name")
        # the name becomes a filesystem folder under the runtime root;
        # reject separators/'..' so generated files can't escape it
        if not self._NAME_RE.match(doc["name"]):
            raise ValueError(
                f"invalid flow name '{doc['name']}': use letters, digits, "
                "'_', '-', '.'"
            )
        mode = (gui.get("input") or {}).get("mode", "streaming")
        if mode not in ("streaming", "batching"):
            raise ValueError(f"unknown input mode '{mode}'")
        itype = (gui.get("input") or {}).get("type", "local")
        if itype not in ("local", "events", "eventhub", "kafka", "iothub", "blobs", "socket", "file"):
            raise ValueError(f"unknown input type '{itype}'")

    def _s400_job_tokens(self, ctx) -> None:
        """Build the token dictionary from gui + environment
        (S400_PrepareJobConfigVariables)."""
        doc = ctx["doc"]
        gui = doc["gui"]
        name = doc["name"]
        iprops = (gui.get("input") or {}).get("properties") or {}
        proc = gui.get("process") or {}
        jobconf = proc.get("jobconfig") or {}

        flow_dir = name  # runtime-storage-relative folder per flow
        tok = TokenDictionary({
            "name": name,
            "cpConfigFolderBase": self.runtime.resolve(""),
            "inputType": (gui.get("input") or {}).get("type", "local"),
            "inputStreamingIntervalInSeconds": str(
                iprops.get("windowDuration") or iprops.get("intervalInSeconds") or "1"
            ),
            "inputStreamingCheckpointDir": os.path.join(
                self.runtime.resolve(flow_dir), "checkpoints"
            ),
            "inputEventHubConnectionString": iprops.get("inputEventhubConnection", ""),
            "inputEventHubConsumerGroup": iprops.get("consumerGroup") or name,
            "inputEventHubCheckpointDir": os.path.join(
                self.runtime.resolve(flow_dir), "eventhub-checkpoints"
            ),
            "inputEventHubCheckpointInterval": str(
                iprops.get("checkpointInterval") or "60"
            ),
            "inputEventHubMaxRate": str(iprops.get("maxRate") or "35000"),
            "inputEventHubFlushExistingCheckpoints": str(
                iprops.get("flushExistingCheckpoints") or "false"
            ).lower(),
            "processTimestampColumn": proc.get("timestampColumn", ""),
            "processWatermark": proc.get("watermark")
            or f"{iprops.get('watermarkValue', 0)} {iprops.get('watermarkUnit', 'second')}",
            "localMetricsHttpEndpoint": iprops.get("localMetricsHttpEndpoint")
            or (doc.get("properties") or {}).get("localMetricsHttpEndpoint", ""),
            "guiJobNumChips": str(
                jobconf.get("jobNumChips")
                or jobconf.get("jobNumExecutors")  # legacy designer field
                or "1"
            ),
            "guiJobBatchCapacity": str(
                jobconf.get("jobBatchCapacity") or "65536"
            ),
            # in-flight window of the pipelined hosts; empty = engine
            # default (runtime/processor.py DEFAULT_PIPELINE_DEPTH)
            "guiJobPipelineDepth": str(
                jobconf.get("jobPipelineDepth") or ""
            ),
            # ingest decoder shard count (native/decoder.cpp sharded
            # decode); empty = engine default (cap 4), env
            # DATAX_DECODER_THREADS stays the operator override
            "guiJobDecoderThreads": str(
                jobconf.get("jobDecoderThreads") or ""
            ),
            # host Prometheus/health port (0/empty = ephemeral); the
            # fleet analyzer's DX413 lint flags co-placed flows that
            # pin the same port
            "guiJobObservabilityPort": str(
                jobconf.get("jobObservabilityPort") or ""
            ),
            # bound on the transfer-helper jit caches; empty = engine
            # default (runtime/processor.py DEFAULT_JIT_CACHE_CAP, the
            # same constant the DX601 compile-surface lint uses)
            "guiJobCompileJitCacheCap": str(
                jobconf.get("jobCompileJitCacheCap") or ""
            ),
            "processedSchemaPath": os.path.join(
                self.runtime.resolve(flow_dir), "processedschema.json"
            ),
        })
        # environment defaults fill tokens the flow left empty
        for k, v in self.env_tokens.items():
            if not tok.get(k):
                tok.set(k, v)
        ctx["tokens"] = tok
        ctx["flow_dir"] = flow_dir

        # input schema: gui carries the schema JSON inline; write to file
        schema_json = iprops.get("inputSchemaFile") or "{}"
        schema_path = os.path.join(ctx["flow_dir"], "inputschema.json")
        ctx["result"].files[schema_path] = (
            schema_json if isinstance(schema_json, str) else json.dumps(schema_json)
        )
        tok.set("inputSchemaFilePath", self.runtime.stored_path(schema_path))

        # additional named input sources (gui.input.sources — the
        # flattenerConfig input.sources map): each gets its own schema/
        # projection artifact and flat datax.job.input.sources.<name>.*
        # keys, enabling multi-source flows (cross-stream window joins)
        # straight from the designer
        ctx["multi_source_keys"] = {}
        for src in (gui.get("input") or {}).get("sources") or []:
            sname = src.get("id") or src.get("name")
            if not sname:
                continue
            if not re.fullmatch(r"[A-Za-z][A-Za-z0-9_-]*", sname):
                # the id becomes a file path segment and a flat conf-key
                # namespace: anything else is a traversal / key-injection
                # vector
                raise ValueError(
                    f"source id {sname!r} must match [A-Za-z][A-Za-z0-9_-]*"
                )
            sprops = src.get("properties") or {}
            ns = f"datax.job.input.sources.{sname}"
            keys = ctx["multi_source_keys"]
            keys[f"{ns}.inputtype"] = (src.get("type") or "local").lower()
            sschema = sprops.get("inputSchemaFile") or "{}"
            spath = os.path.join(ctx["flow_dir"], "sources",
                                 f"{sname}.schema.json")
            ctx["result"].files[spath] = (
                sschema if isinstance(sschema, str) else json.dumps(sschema)
            )
            keys[f"{ns}.blobschemafile"] = self.runtime.stored_path(spath)
            if sprops.get("target"):
                keys[f"{ns}.target"] = sprops["target"]
            snippet = sprops.get("normalizationSnippet")
            if snippet:
                ppath = os.path.join(ctx["flow_dir"], "sources",
                                     f"{sname}.projection")
                ctx["result"].files[ppath] = snippet
                keys[f"{ns}.projection"] = self.runtime.stored_path(ppath)
            # remaining scalar properties pass through lowercased
            # (kafka.topics, socket.port, maxRate, ...) — key charset
            # restricted and newlines rejected: conf is line-based
            # key=value text, so either would inject arbitrary lines
            for pk, pv in sprops.items():
                if pk in ("inputSchemaFile", "target",
                          "normalizationSnippet") or pv in (None, "", [], {}):
                    continue
                if not isinstance(pv, (str, int, float, bool)):
                    continue
                if not re.fullmatch(r"[A-Za-z0-9_.-]+", pk):
                    raise ValueError(
                        f"source property key {pk!r} must match "
                        "[A-Za-z0-9_.-]+"
                    )
                sv = str(pv)
                if "\n" in sv or "\r" in sv:
                    raise ValueError(
                        f"source property {pk!r} value must be single-line"
                    )
                keys[f"{ns}.{pk.lower()}"] = sv

        # reference data passes straight through as the template value
        tok.set("inputReferenceData", [
            {
                "name": rd.get("id"),
                "path": (rd.get("properties") or {}).get("path", ""),
                "format": rd.get("type", "csv"),
                "header": str((rd.get("properties") or {}).get("header", "true")),
                "delimiter": (rd.get("properties") or {}).get("delimiter", ","),
            }
            for rd in (gui.get("input") or {}).get("referenceData") or []
        ])

    def _s450_transform(self, ctx) -> None:
        """Queries + rules -> transform script via the codegen engine
        (S450_GenerateTransformFile + CodegenRules Engine.GenerateCode)."""
        doc = ctx["doc"]
        gui = doc["gui"]
        queries = (gui.get("process") or {}).get("queries") or []
        code = "\n".join(q if isinstance(q, str) else str(q) for q in queries)
        rules_json = self.rule_gen.generate(gui.get("rules") or [], doc["name"])
        windowable = {"DataXProcessedInput"}
        for src in (gui.get("input") or {}).get("sources") or []:
            sname = src.get("id") or src.get("name")
            if sname:
                windowable.add(
                    (src.get("properties") or {}).get("target") or sname
                )
        rules_code: RulesCode = self.codegen.generate_code(
            code, rules_json, doc["name"], windowable_tables=windowable
        )
        ctx["rules_code"] = rules_code

        transform_path = os.path.join(ctx["flow_dir"], f"{doc['name']}.transform")
        ctx["result"].files[transform_path] = rules_code.code
        ctx["tokens"].set("processTransforms",
                          self.runtime.stored_path(transform_path))

    def _s500_resolve(self, ctx) -> None:
        """Resolve projections, UDFs, time windows, state tables, outputs
        (S500_ResolveProcessTemplate / ResolveOutputs)."""
        doc = ctx["doc"]
        gui = doc["gui"]
        tok: TokenDictionary = ctx["tokens"]
        rules_code: RulesCode = ctx["rules_code"]
        iprops = (gui.get("input") or {}).get("properties") or {}

        # projection file: normalization snippet (or Raw.* passthrough)
        normalization = iprops.get("normalizationSnippet") or "Raw.*"
        proj_path = os.path.join(ctx["flow_dir"], f"{doc['name']}.projection")
        ctx["result"].files[proj_path] = normalization
        tok.set("processProjections", [self.runtime.stored_path(proj_path)])

        # functions -> jar UDFs / UDAFs / azure functions template arrays
        jar_udfs, jar_udafs, azure_fns = [], [], []
        for fn in (gui.get("process") or {}).get("functions") or []:
            props = fn.get("properties") or {}
            entry = {
                "name": fn.get("id"),
                "class": props.get("class") or props.get("module", ""),
                "path": props.get("path", ""),
                "libs": props.get("libs") or [],
            }
            ftype = (fn.get("type") or "").lower()
            if ftype in ("jarudf", "udf", "pythonudf"):
                jar_udfs.append(entry)
            elif ftype in ("jarudaf", "udaf"):
                jar_udafs.append(entry)
            elif ftype == "azurefunction":
                azure_fns.append({
                    "name": fn.get("id"),
                    "serviceEndpoint": props.get("serviceEndpoint", ""),
                    "api": props.get("api", ""),
                    "code": props.get("code", ""),
                    "methodType": props.get("methodType", "get"),
                    "params": props.get("params") or [],
                })
        tok.set("processJarUDFs", jar_udfs)
        tok.set("processJarUDAFs", jar_udafs)
        tok.set("processAzureFunctions", azure_fns)

        # time windows from codegen's TIMEWINDOW extraction
        tok.set("processTimeWindows", [
            {"name": n, "windowDuration": d}
            for n, d in sorted(rules_code.time_windows.items())
        ])

        # accumulation (state) tables from --DataXStates--
        tok.set("processStateTables", [
            {
                "name": n,
                "schema": s,
                "location": os.path.join(
                    self.runtime.resolve(ctx["flow_dir"]), "statetables", n
                ),
            }
            for n, s in sorted(rules_code.accumulation_tables.items())
        ])

        # outputs: gui sink definitions keyed by id
        sink_defs: Dict[str, dict] = {}
        for out in gui.get("outputs") or []:
            sink_defs[out.get("id")] = out

        # codegen's OUTPUT tables TO sink (tables may be comma-separated)
        table_sinks: Dict[str, List[str]] = {}
        for tables, sink_name in rules_code.outputs:
            for table in tables.split(","):
                table_sinks.setdefault(table.strip(), []).append(sink_name)

        outputs_arr: List[dict] = []
        for table, sinks in sorted(table_sinks.items()):
            entry: Dict[str, Any] = {"name": table}
            for sname in sinks:
                sdef = sink_defs.get(sname)
                stype = (sdef.get("type") if sdef else "metric") or "metric"
                props = (sdef.get("properties") if sdef else {}) or {}
                if stype == "metric":
                    entry["metric"] = "enabled"
                elif stype in ("blob", "file", "local"):
                    entry["file"] = {
                        "path": props.get("folder")
                        or props.get("path")
                        or os.path.join(
                            self.runtime.resolve(ctx["flow_dir"]), "out", table
                        ),
                        "compressionType": props.get("compressionType", "none"),
                        "format": props.get("format", "json"),
                    }
                elif stype == "httppost":
                    entry["httppost"] = {
                        "endpoint": props.get("endpoint", ""),
                        "filter": props.get("filter", ""),
                    }
                elif stype == "console":
                    entry["console"] = {"maxRows": props.get("maxRows", 20)}
                elif stype == "eventhub":
                    entry["eventhub"] = {
                        "connectionStringRef": props.get("connection", ""),
                        "compressionType": props.get("compressionType", "gzip"),
                    }
                elif stype in ("externalfn", "azurefunction"):
                    entry["externalfn"] = {
                        "serviceEndpoint": props.get("serviceEndpoint", ""),
                        "api": props.get("api", ""),
                        "code": props.get("code", ""),
                        "methodType": props.get("methodType", "post"),
                    }
                elif stype == "cosmosdb":
                    entry["cosmosdb"] = {
                        "connectionStringRef": props.get("connection", ""),
                        "database": props.get("db", ""),
                        "collection": props.get("collection", ""),
                    }
            outputs_arr.append(entry)
        tok.set("outputs", outputs_arr)

    def _s550_batch(self, ctx) -> None:
        """Batch-mode inputs: start/end/path/partition increment
        (S550_ProduceBatchInput). Streaming flows: no-op."""
        gui = ctx["doc"]["gui"]
        if (gui.get("input") or {}).get("mode") != "batching":
            return
        iprops = (gui.get("input") or {}).get("properties") or {}
        batches = (gui.get("batch") or [])
        ctx["batch_inputs"] = [
            {
                "path": (b.get("properties") or {}).get("path", iprops.get("path", "")),
                "startTime": (b.get("properties") or {}).get("startTime", ""),
                "endTime": (b.get("properties") or {}).get("endTime", ""),
                "partitionIncrement": (b.get("properties") or {}).get(
                    "partitionIncrement", "1"
                ),
            }
            for b in batches
        ] or [{"path": iprops.get("path", ""), "startTime": "", "endTime": "",
               "partitionIncrement": "1"}]

    def _s600_job_configs(self, ctx) -> None:
        """Resolve the template per job entry with all tokens
        (S600_GenerateJobConfig)."""
        doc = ctx["doc"]
        cp = doc["commonProcessor"]
        tok: TokenDictionary = ctx["tokens"]
        job_configs: List[tuple] = []
        for i, job in enumerate(cp.get("jobs") or [{}]):
            jt = TokenDictionary()
            jt.update({n: tok.get(n) for n in tok.names()})
            for k, v in {**ctx.get("job_common", {}), **job}.items():
                jt.set(k, jt.replace(v))
            resolved = jt.replace(copy.deepcopy(cp["template"]))
            job_name = jt.get("tpuJobName") or f"DataXTpu-{doc['name']}"
            if len(cp.get("jobs") or []) > 1:
                job_name = f"{job_name}-{i + 1}"
            leftover = set(unresolved_tokens(resolved))
            if leftover:
                logger.warning("unresolved tokens in %s: %s", job_name, leftover)
            job_configs.append((job_name, resolved, jt))
        ctx["job_configs"] = job_configs

    def _s620_conformance(self, ctx) -> None:
        """Embed the flow's machine-readable cost-model report and the
        default alert rules into the generated conf, making the DX2xx
        static prediction a *runtime artifact* the host's
        ConformanceMonitor and AlertEngine read
        (``datax.job.process.conformance.model`` /
        ``datax.job.process.alerts.rules``; obs/conformance.py,
        obs/alerts.py).

        Fail-open: the conformance model rides on the device analyzer
        (the same lowering the job will run); an analyzer error must
        not block deployment — the job simply runs unmonitored, like
        every job did before this layer existed. Opt out with designer
        jobconfig ``jobConformanceModel: "false"``."""
        doc = ctx["doc"]
        jobconf = (doc["gui"].get("process") or {}).get("jobconfig") or {}
        ctx["conformance_json"] = None
        if str(jobconf.get("jobConformanceModel", "")).lower() != "false":
            try:
                from ..analysis import analyze_flow_device

                report = analyze_flow_device(doc)
                if report.stages:
                    ctx["conformance_json"] = json.dumps(
                        report.runtime_model(), separators=(",", ":")
                    )
            except Exception as e:  # noqa: BLE001 — monitoring is optional
                logger.warning(
                    "conformance model generation failed for %s: %s",
                    doc.get("name"), e,
                )
        from ..obs.alerts import default_rules

        ctx["alert_rules_json"] = json.dumps(
            default_rules(doc.get("name")), separators=(",", ":")
        )

    def _s630_compile(self, ctx) -> None:
        """Emit the flow's AOT **compile manifest** as a deployment
        artifact and wire the persistent compilation cache — the
        reference compiled Flow JSON into a deployable job artifact
        ahead of time (SURVEY §1 L3, DataX.Config -> flat .conf ->
        spark-submit); ours additionally ships the *compiled
        executables' coordinates*: the compile-surface analyzer
        (``analysis/compilecheck.py``) proves the flow's jit entry set
        finite, the manifest lands beside the conf
        (``<flow>/compile.manifest.json``), and the conf points at it
        (``datax.job.process.compile.manifest``) so ``FlowProcessor``
        AOT-warms every entry at init instead of first dispatch.

        The cache conf rides along: ``compile.cachedir`` under the
        flow's runtime folder (restarts deserialize instead of
        recompiling), and — when runtime storage is the shared object
        store — ``compile.cacheurl`` (an ``objstore://`` prefix) so
        preemption-recovered and scaled-out replicas pull compiles
        their peers already paid for.

        Fail-open like S620: an analyzer error must not block
        deployment — the job simply cold-starts like every job did
        before this layer existed. Opt out with designer jobconfig
        ``jobCompileManifest: "false"``. Skipped for multi-chip jobs
        (mesh shardings change the lowering; the manifest is a
        single-chip artifact for now)."""
        doc = ctx["doc"]
        jobconf = (doc["gui"].get("process") or {}).get("jobconfig") or {}
        ctx["compile_manifest_path"] = None
        chips = str(
            jobconf.get("jobNumChips")
            or jobconf.get("jobNumExecutors") or "1"
        )
        if (
            str(jobconf.get("jobCompileManifest", "")).lower() != "false"
            and chips in ("", "1")
        ):
            try:
                from ..analysis import analyze_flow_compile

                report = analyze_flow_compile(doc)
                if report.manifest and report.manifest.get("entries"):
                    mpath = os.path.join(
                        ctx["flow_dir"], "compile.manifest.json"
                    )
                    ctx["result"].files[mpath] = json.dumps(
                        report.manifest, separators=(",", ":")
                    )
                    ctx["compile_manifest_path"] = (
                        self.runtime.stored_path(mpath)
                    )
            except Exception as e:  # noqa: BLE001 — AOT is an optimization
                logger.warning(
                    "compile manifest generation failed for %s: %s",
                    doc.get("name"), e,
                )
        ctx["compile_cache_dir"] = os.path.join(
            self.runtime.resolve(ctx["flow_dir"]), "compilecache"
        )
        ctx["compile_cache_url"] = None
        client = getattr(self.runtime, "client", None)
        if client is not None and hasattr(client, "url_for"):
            ctx["compile_cache_url"] = client.url_for(
                f"{ctx['flow_dir']}/compilecache".replace(os.sep, "/")
            )

    def _s640_pilot(self, ctx) -> None:
        """Wire the autopilot (``pilot/controller.py``) into the
        generated conf: ``datax.job.process.pilot.*`` from the designer
        ``jobPilot*`` knobs. Default ON — a generated job runs piloted
        (depth/backpressure actuation bounded by budget + cooldown)
        unless the designer sets ``jobPilot: "false"``. The stall-EWMA
        half-life (``jobStallEwmaMs`` ->
        ``observability.stallewmams``) rides along so /readyz and the
        controller judge "stalled" off one conf'd constant."""
        doc = ctx["doc"]
        jobconf = (doc["gui"].get("process") or {}).get("jobconfig") or {}
        keys: Dict[str, str] = {}
        if str(jobconf.get("jobPilot", "")).lower() == "false":
            keys["datax.job.process.pilot.enabled"] = "false"
        for gui_key, conf_key in (
            ("jobPilotWindowSeconds", "pilot.windowseconds"),
            ("jobPilotCooldownSeconds", "pilot.cooldownseconds"),
            ("jobPilotBudget", "pilot.budget"),
            ("jobPilotMaxDepth", "pilot.maxdepth"),
            ("jobPilotMaxReplicas", "pilot.maxreplicas"),
            ("jobStallEwmaMs", "observability.stallewmams"),
            # PR 12 time-model surface: the on-demand profiler endpoint,
            # the per-batch HBM watermark sampler and machine-profile
            # calibration (all default ON in the host; these designer
            # knobs exist to turn one off per job)
            ("jobProfiler", "observability.profiler"),
            ("jobHbmSample", "observability.hbmsample"),
            ("jobCalibration", "observability.calibration"),
            # LiveQuery serving plane (lq/service.py): dispatch-tick
            # deadline, per-tenant quotas and the warm-kernel HBM
            # budget ride in the conf like every other process knob,
            # so a serving plane built from this flow's conf honors
            # the designer's choices
            ("jobLqMaxBatchWaitMs", "lq.maxbatchwaitms"),
            ("jobLqMaxFanin", "lq.maxfanin"),
            ("jobLqSessionTtlSeconds", "lq.sessionttlseconds"),
            ("jobLqMaxSessions", "lq.maxsessions"),
            ("jobLqTenantMaxSessions", "lq.tenant.maxsessions"),
            ("jobLqTenantMaxQps", "lq.tenant.maxqps"),
            ("jobLqHbmBudgetMb", "lq.hbmbudgetmb"),
        ):
            v = jobconf.get(gui_key)
            if v not in (None, ""):
                keys[f"datax.job.process.{conf_key}"] = str(v)
        ctx["pilot_keys"] = keys

    def _s660_mesh(self, ctx) -> None:
        """Embed the flow's **sharding-plan artifact** into mesh jobs'
        confs (``datax.job.process.mesh.model``): the DX7xx
        mesh-sharding analyzer's per-stage collective byte model
        (``analysis/meshcheck.py``), the prediction the host's
        ``ConformanceMonitor`` compares against the observed
        ``Mesh_ICI_Bytes`` / ``Mesh_Reshard_Count`` series at runtime
        (DX510/DX511 ICI drift, beside S620's DX501-503 model).

        Single-chip jobs skip it (no mesh, no collectives to model).
        The analyzer runs model-only here (``lower=False`` — no
        per-stage compiles on the deploy path; tier-1 proves the model
        equals the lowering). Fail-open like S620/S630: an analyzer
        error must not block deployment — the mesh job simply runs
        without ICI conformance, like every mesh job did before this
        layer existed. Opt out with designer jobconfig ``jobMeshModel:
        "false"``."""
        doc = ctx["doc"]
        jobconf = (doc["gui"].get("process") or {}).get("jobconfig") or {}
        ctx["mesh_json"] = None
        chips_s = str(
            jobconf.get("jobNumChips")
            or jobconf.get("jobNumExecutors") or "1"
        )
        try:
            chips = int(chips_s)
        except ValueError:
            chips = 1
        if (
            chips > 1
            and str(jobconf.get("jobMeshModel", "")).lower() != "false"
        ):
            try:
                from ..analysis import analyze_flow_mesh

                report = analyze_flow_mesh(doc, chips=chips, lower=False)
                if report.stages:
                    ctx["mesh_json"] = json.dumps(
                        report.runtime_model(), separators=(",", ":")
                    )
            except Exception as e:  # noqa: BLE001 — monitoring is optional
                logger.warning(
                    "mesh model generation failed for %s: %s",
                    doc.get("name"), e,
                )

    def _s650_flatten(self, ctx) -> None:
        """Flatten each resolved job config JSON to flat conf text
        (S650 ConfigFlattener.Flatten)."""
        flattener = ConfigFlattener(DEFAULT_FLATTENER_SCHEMA)
        ctx["flat_confs"] = []
        for job_name, resolved, jt in ctx["job_configs"]:
            flat = flattener.flatten(self._prune(resolved))
            extra = {}
            if jt.get("jobBatchCapacity"):
                extra["datax.job.process.batchcapacity"] = str(
                    jt.get("jobBatchCapacity"))
            if jt.get("jobNumChips"):
                extra["datax.job.process.numchips"] = str(jt.get("jobNumChips"))
            if jt.get("jobPipelineDepth"):
                extra["datax.job.process.pipeline.depth"] = str(
                    jt.get("jobPipelineDepth"))
            if jt.get("jobDecoderThreads"):
                extra["datax.job.process.ingest.decoderthreads"] = str(
                    jt.get("jobDecoderThreads"))
            if jt.get("jobObservabilityPort"):
                extra["datax.job.process.observability.port"] = str(
                    jt.get("jobObservabilityPort"))
            if jt.get("telemetryTraceFile"):
                # one flight recorder for control plane + jobs (the
                # env-token wiring serve/__main__ uses so `obs trace`
                # renders the whole cross-process tree from one file)
                extra["datax.job.process.telemetry.tracefile"] = str(
                    jt.get("telemetryTraceFile"))
            if jt.get("fleetPublishUrl"):
                # fleet telemetry plane (obs/publisher.py): spawned
                # hosts publish windowed frames to the control plane's
                # shared objstore so FleetView can roll them up — the
                # env-token wiring serve/__main__ sets when an object
                # store is configured
                extra["datax.job.process.fleet.publishurl"] = str(
                    jt.get("fleetPublishUrl"))
            if ctx.get("conformance_json"):
                extra["datax.job.process.conformance.model"] = (
                    ctx["conformance_json"])
            if ctx.get("alert_rules_json"):
                extra["datax.job.process.alerts.rules"] = (
                    ctx["alert_rules_json"])
            if ctx.get("mesh_json"):
                extra["datax.job.process.mesh.model"] = ctx["mesh_json"]
            if ctx.get("compile_manifest_path"):
                extra["datax.job.process.compile.manifest"] = (
                    ctx["compile_manifest_path"])
            if ctx.get("compile_cache_dir"):
                extra["datax.job.process.compile.cachedir"] = (
                    ctx["compile_cache_dir"])
            if ctx.get("compile_cache_url"):
                extra["datax.job.process.compile.cacheurl"] = (
                    ctx["compile_cache_url"])
            if jt.get("jobCompileJitCacheCap"):
                extra["datax.job.process.compile.jitcachecap"] = str(
                    jt.get("jobCompileJitCacheCap"))
            for b_i, b in enumerate(ctx.get("batch_inputs") or []):
                ns = f"datax.job.input.batch.blob.{b_i}"
                for k, v in b.items():
                    if v:
                        extra[f"{ns}.{k.lower()}"] = str(v)
            extra.update(ctx.get("pilot_keys") or {})
            extra.update(ctx.get("multi_source_keys") or {})
            flat.update(extra)
            conf_text = "\n".join(f"{k}={v}" for k, v in sorted(flat.items()))
            ctx["flat_confs"].append((job_name, conf_text))

    @staticmethod
    def _prune(value):
        """Drop empty strings/dicts/lists so absent features emit no keys
        (the reference's conf omits unset namespaces entirely)."""
        if isinstance(value, dict):
            out = {}
            for k, v in value.items():
                pv = RuntimeConfigGeneration._prune(v)
                if pv not in ("", None) and pv != {} and pv != []:
                    out[k] = pv
            return out
        if isinstance(value, list):
            return [RuntimeConfigGeneration._prune(v) for v in value]
        return value

    def _s700_write_files(self, ctx) -> None:
        """Write transform/projection/schema + conf files
        (S700_DeployConfigFiles)."""
        result: GenerationResult = ctx["result"]
        for rel, content in result.files.items():
            self.runtime.save_file(rel, content)
        for job_name, conf_text in ctx["flat_confs"]:
            rel = os.path.join(ctx["flow_dir"], f"{job_name}.conf")
            path = self.runtime.save_file(rel, conf_text + "\n")
            result.conf_paths.append(path)
            result.job_names.append(job_name)

    def _s800_jobs(self, ctx) -> None:
        """Upsert job records (S800_DeploySparkJob.cs:23-60)."""
        for job_name, conf_path in zip(
            ctx["result"].job_names, ctx["result"].conf_paths
        ):
            existing = self.jobs.get(job_name)
            self.jobs.upsert({
                "name": job_name,
                "flow": ctx["doc"]["name"],
                "confPath": conf_path,
                "state": (existing or {}).get("state") or "idle",
            })

    def _s850_metrics(self, ctx) -> None:
        """Attach the auto-generated metrics dashboard config
        (S850_DeployMetricsConfig + CodegenRules Metrics.cs)."""
        rules_code: RulesCode = ctx["rules_code"]
        if rules_code.metrics_root:
            ctx["doc"]["metrics"] = rules_code.metrics_root
            ctx["result"].metrics = rules_code.metrics_root

    def _s900_finalize(self, ctx) -> None:
        """Persist the updated flow doc with jobNames (S900_FinishUp)."""
        ctx["doc"]["jobNames"] = ctx["result"].job_names
        self.design.save(ctx["doc"])
