"""data_accelerator_tpu — a TPU-native streaming analytics framework.

A ground-up rebuild of the capabilities of Microsoft Data Accelerator
(reference: itshawi/data-accelerator): self-service streaming "Flows"
(input stream -> normalization/projection -> DataXQuery SQL + no-code rules
with time windows, accumulators and UDFs -> sinks + live metrics), compiled
to XLA kernels on TPU instead of Spark jobs on a JVM cluster.

Layer map (vs. reference layers, see SURVEY.md):
- ``core``      columnar batches, schemas, flat ``datax.job.*`` config
                (reference: datax-core config/, Spark DataFrames)
- ``compile``   Flow compiler: DataXQuery parser, rules codegen, SQL subset
                planner, flow-JSON flattener
                (reference: datax.sql.TransformSQLParser, DataX.Flow.CodegenRules,
                DataX.Config flattener)
- ``ops``       jax/Pallas kernels: filter, hash-groupby, join, windowed
                aggregation (reference: delegated to Spark SQL execution)
- ``runtime``   micro-batch streaming host, sources, sinks, checkpointing,
                state tables (reference: datax-host host/, input/, sink/)
- ``parallel``  device-mesh sharding, ICI collectives in place of shuffle
                (reference: Spark partitioning + Netty shuffle)
- ``extension`` UDF tiers incl. the Pallas escape hatch
                (reference: datax.extension.DynamicUDF, JarUDF)
- ``serve``     control-plane REST, LiveQuery kernels, schema inference
                (reference: Services/DataX.Flow.*)
- ``obs``       metrics store + emission (reference: DataX.Metrics + Redis)
"""

__version__ = "0.1.0"

import os as _os

# The TPU-tunnel sitecustomize pins jax.config's jax_platforms at
# interpreter start, silently overriding the JAX_PLATFORMS env var. Make
# the env var authoritative for this framework's processes (CLI hosts,
# tests, bench drivers all select their platform via env).
_env_platforms = _os.environ.get("JAX_PLATFORMS")
if _env_platforms:
    import jax as _jax

    if (_jax.config.jax_platforms or "") != _env_platforms:
        try:
            _jax.config.update("jax_platforms", _env_platforms)
        except RuntimeError:
            pass  # backends already initialized; too late to switch

del _os
