"""Batch-granular span tracing for the streaming/batch engines.

Every micro-batch gets a ``trace_id``; every stage the host runs on its
behalf (decode -> dispatch -> device step -> completion sync -> collect
-> per-sink writes -> checkpoint) becomes a ``span`` record carrying
``span_id``/``parent_id``, start timestamp and duration. Spans are
emitted through the existing ``TelemetryWriter`` fan-out
(obs/telemetry.py), so the JSONL flight recorder doubles as a trace log
a CLI can reconstruct: ``python -m data_accelerator_tpu.obs trace
<batch_id>`` rebuilds one batch's span tree.

reference: the AppInsights operation-correlation the reference gets for
free from DataX.Utilities.Telemetry (every ``streaming/batch/*`` event
shares an operation id); here the correlation is explicit and the store
is pluggable.

Design notes:
- Span boundaries are wall-clock host timestamps (``time.time`` for the
  epoch anchor, ``perf_counter`` for durations) — overhead is two clock
  reads and one dict per span; there is no per-row work.
- A thread-local *active trace* lets deep code (sinks, checkpointers,
  the processor's collect path) attach child spans without threading a
  context object through every signature: ``with tracing.span("x"):``
  is a no-op when no trace is active (e.g. bench.py driving the
  processor directly).
- Cross-thread stages (the pipelined decode-ahead worker) re-activate
  the batch's context explicitly via ``ctx.activate()``.
- Every finished span also feeds the per-stage latency histograms
  (obs/histogram.py) when the tracer holds a registry — spans and
  histograms cannot disagree because they share the one measurement.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import struct
import threading
import time
from typing import Dict, Iterator, Optional

from .histogram import HistogramRegistry

_local = threading.local()

_trace_counter = itertools.count(1)


def _new_trace_id() -> str:
    """Unique, sortable-enough trace id: epoch-ms + 4 random bytes."""
    rnd = struct.unpack("<I", os.urandom(4))[0]
    return f"{int(time.time() * 1000):x}-{rnd:08x}"


def _span_prefix() -> str:
    """Random per-context span-id prefix, used when a context JOINS an
    existing trace (cross-process propagation): span ids are minted by
    a per-context counter, so two processes sharing one trace id need
    disjoint id spaces or their span ids collide."""
    return f"{struct.unpack('<I', os.urandom(4))[0]:08x}."


def format_parent(cap) -> Optional[str]:
    """Serialize a ``capture()`` as the ``<trace_id>:<span_id>`` string
    the ``datax.job.process.telemetry.parenttrace`` conf key carries
    across the process boundary (control plane -> spawned host)."""
    if cap is None:
        return None
    ctx, parent_id = cap
    return f"{ctx.trace_id}:{parent_id}"


def parse_parent(text: Optional[str]):
    """Inverse of ``format_parent``: ``(trace_id, span_id)`` or None."""
    if not text or ":" not in text:
        return None
    trace_id, _, span_id = text.rpartition(":")
    if not trace_id or not span_id:
        return None
    return trace_id, span_id


def current_trace() -> Optional["TraceContext"]:
    """The trace active on THIS thread (None outside any batch)."""
    stack = getattr(_local, "stack", None)
    return stack[-1][0] if stack else None


def capture():
    """Opaque (trace, parent-span) capture of this thread's active
    position, for handing to a worker thread (the sink fan-out runs one
    thread per output operator)."""
    stack = getattr(_local, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def activated(cap) -> Iterator[None]:
    """Re-activate a ``capture()`` on another thread; no-op for None."""
    if cap is None:
        yield
        return
    ctx, parent_id = cap
    with ctx.activate(parent_id=parent_id):
        yield


@contextlib.contextmanager
def span(name: str, **props) -> Iterator[None]:
    """Child span under the thread's active trace; no-op without one.

    The no-op path costs one attribute lookup — safe to leave in hot
    host code permanently (sinks, checkpoint, collect)."""
    stack = getattr(_local, "stack", None)
    if not stack:
        yield
        return
    ctx, parent_id = stack[-1]
    with ctx._child(name, parent_id, props):
        yield


class TraceContext:
    """One batch's trace: a root span plus explicitly-parented children.

    With ``trace_id``/``parent_span_id`` the context JOINS an existing
    (possibly remote) trace instead of minting one: the root span keeps
    a parent pointer into the foreign trace and every span id carries a
    random per-context prefix so concurrent contexts — other batches of
    the same job, other processes — cannot collide inside the shared
    trace."""

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        props: Dict,
        trace_id: Optional[str] = None,
        parent_span_id: Optional[str] = None,
    ):
        self.tracer = tracer
        self.parent_span_id = parent_span_id
        if trace_id is not None:
            self.trace_id = trace_id
            prefix = _span_prefix()
        else:
            self.trace_id = _new_trace_id()
            prefix = ""
        self.root_span_id = prefix + "1"
        self._span_counter = (
            prefix + str(n) for n in itertools.count(2)
        )
        self._name = name
        self._props = dict(props)
        self._start_ts = time.time()
        self._start_pc = time.perf_counter()
        self._ended = False
        self._lock = threading.Lock()
        # named timestamps for spans whose endpoints are observed at
        # different call sites (e.g. device-step: dispatch return ->
        # completion sync)
        self.marks: Dict[str, tuple] = {}

    # -- root ------------------------------------------------------------
    def add(self, **props) -> None:
        """Attach properties to the root span (e.g. batchTime once the
        poll has determined it)."""
        self._props.update(props)

    def end(self, **props) -> None:
        """Close the root span (idempotent — a retry path may race the
        normal close)."""
        with self._lock:
            if self._ended:
                return
            self._ended = True
        self._props.update(props)
        self.tracer._emit_span(
            self, self._name, self.root_span_id, self.parent_span_id,
            self._start_ts, (time.perf_counter() - self._start_pc) * 1000.0,
            self._props,
        )

    # -- children --------------------------------------------------------
    @contextlib.contextmanager
    def activate(self, parent_id: Optional[str] = None) -> Iterator["TraceContext"]:
        """Install as the thread's active trace (children created via the
        module-level ``span()`` parent onto the root — or onto
        ``parent_id`` when re-activating a captured position — or the
        innermost open span of THIS thread)."""
        stack = getattr(_local, "stack", None)
        if stack is None:
            stack = _local.stack = []
        stack.append((self, parent_id or self.root_span_id))
        try:
            yield self
        finally:
            stack.pop()

    @contextlib.contextmanager
    def span(self, name: str, **props) -> Iterator[None]:
        """Explicit child of the root — usable from any thread without
        activation (the pipelined loop holds several batches at once)."""
        with self._child(name, self.root_span_id, props):
            yield

    def mark(self, name: str) -> None:
        """Remember 'now' under ``name`` (see ``record_since``)."""
        self.marks[name] = (time.time(), time.perf_counter())

    def record_since(self, name: str, mark: str, **props) -> None:
        """Emit a span from a prior ``mark()`` to now (no-op when the
        mark was never set)."""
        m = self.marks.get(mark)
        if m is None:
            return
        self.record(
            name, m[0], (time.perf_counter() - m[1]) * 1000.0, **props
        )

    def record(self, name: str, start_ts: float, duration_ms: float,
               **props) -> None:
        """A span whose boundaries were measured externally (e.g. the
        device-step interval between dispatch return and completion
        sync, whose endpoints the host observed at different places)."""
        self.tracer._emit_span(
            self, name, str(next(self._span_counter)), self.root_span_id,
            start_ts, duration_ms, props,
        )

    @contextlib.contextmanager
    def _child(self, name: str, parent_id: str, props: Dict) -> Iterator[None]:
        span_id = str(next(self._span_counter))
        start_ts = time.time()
        t0 = time.perf_counter()
        stack = getattr(_local, "stack", None)
        pushed = False
        if stack is not None and stack and stack[-1][0] is self:
            # nest further children under this span on the same thread
            stack.append((self, span_id))
            pushed = True
        try:
            yield
        finally:
            if pushed:
                stack.pop()
            self.tracer._emit_span(
                self, name, span_id, parent_id, start_ts,
                (time.perf_counter() - t0) * 1000.0, props,
            )


class Tracer:
    """Factory for per-batch traces, bound to a flow's telemetry fan-out
    and (optionally) the per-stage histogram registry.

    ``parent``: a ``<trace_id>:<span_id>`` string (the
    ``datax.job.process.telemetry.parenttrace`` conf value) — every
    trace this tracer begins then JOINS that trace instead of minting
    its own, so a spawned host's batch spans root in the control-plane
    request that launched the job."""

    def __init__(
        self,
        telemetry=None,
        histograms: Optional[HistogramRegistry] = None,
        flow: str = "",
        enabled: bool = True,
        parent: Optional[str] = None,
    ):
        self.telemetry = telemetry
        self.histograms = histograms
        self.flow = flow
        self.enabled = enabled
        self.parent = parse_parent(parent)

    def begin(self, name: str = "streaming/batch", **props) -> TraceContext:
        if self.parent is not None:
            return TraceContext(
                self, name, props,
                trace_id=self.parent[0], parent_span_id=self.parent[1],
            )
        return TraceContext(self, name, props)

    def _emit_span(
        self, ctx: TraceContext, name: str, span_id: str,
        parent_id: Optional[str], start_ts: float, duration_ms: float,
        props: Dict,
    ) -> None:
        # histograms always observe (they are the live latency source
        # even when span emission is turned off); the root span's
        # "streaming/" prefix is stripped so its stage is "batch"
        if self.histograms is not None:
            stage = name[10:] if name.startswith("streaming/") else name
            # the span's trace id rides along as the histogram exemplar
            # (a latency spike links back to the batch that caused it)
            self.histograms.observe(
                self.flow, stage, duration_ms, trace_id=ctx.trace_id
            )
        if not self.enabled or self.telemetry is None:
            return
        self.telemetry.track_span(
            name,
            trace_id=ctx.trace_id,
            span_id=span_id,
            parent_id=parent_id,
            start_ts=start_ts,
            duration_ms=duration_ms,
            properties=props,
        )
