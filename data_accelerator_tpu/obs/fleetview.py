"""Control-plane fleet view: cross-replica telemetry rollup, trace
lineage, and the delivery-conservation audit (DX54x).

The pull/merge half of the fleet telemetry plane (push half:
obs/publisher.py). ``FleetView`` lists the telemetry frames replicas
published to the shared object store (``<prefix>/fleet/<flow>/
<replica>/<window>.json``) and aggregates them into fleet-level series:

- **counters summed** across replicas and windows (each frame carries
  windowed deltas, so the running sum is the fleet lifetime total and
  the per-frame points written into the fleet ``MetricStore`` are a
  merge-by-addition time series under the same ``DATAX-<flow>:<metric>``
  keys the per-process stack uses);
- **fixed-bucket histograms merged exactly** via
  ``LatencyHistogram.merge`` (bucket counts added element-wise, raw
  sample windows unioned — merged percentiles equal percentiles over
  the unioned observations);
- **per-replica breakdowns retained** (the SPA fleet tab and
  ``obs fleet`` render both the rollup and the per-replica rows);
- replicas quiet for more than ``stale_windows`` windows are marked
  **stale** unless their last frame carried the ``final`` drain marker
  (then they are **completed** — a clean handoff, not a death).

On top of the rollup:

- **fleet-scope alerts**: an ``AlertEngine`` per flow evaluates the
  same rule dicts (obs/alerts.py, verbatim — ``default_rules`` unless
  injected) over the MERGED store/histograms/health, so an error-budget
  burn is computed over fleet totals, not any single replica's slice;
- **trace lineage**: the replica succession of a flow across
  rescale/handoff, from the job registry's records (``replicaOf`` /
  ``statePartitionMap``, serve/jobs.py) when available, else derived
  from frame arrival order — what ``obs trace`` and the SPA use to
  stitch one continuous cross-replica tree;
- the **delivery-conservation audit**::

      | code  | name                   | meaning |
      |-------|------------------------|---------|
      | DX540 | delivery-loss          | Σ ingested > Σ emitted on the audited output across the lineage — events entered the lineage and never came out |
      | DX541 | delivery-duplication   | Σ emitted > Σ ingested — an offset range was emitted by more than one replica |
      | DX542 | stale-replica          | a replica went quiet past the stale horizon without its final drain frame — its in-flight window is unaccounted |

  Frames count ``ingested`` per source from the post-filter
  ``Input_*_Events_Count`` deltas of acked batches only (a failed batch
  never reaches ``_finish_tail``'s metric emit), and ``emitted`` per
  output from ``Output_*_Events_Count`` — so for a passthrough output
  the two conserve exactly across a rescale lineage, which is what the
  chaos drill asserts (serve/scenarios.py). Aggregating outputs
  (windowed GROUP BYs) under-emit by construction; the audit therefore
  judges one output — the caller's choice, defaulting to the output
  with the highest emitted total.

**Fail-open**: a corrupt/truncated/unreadable frame is skipped and
counted (``Fleet_FrameDecodeError_Count``) — the aggregator never
crashes on bad input, and a flaky store only delays the rollup
(tested with an injected-transport stub, tests/test_fleetview.py).

Surfaced at ``GET /fleet/metrics`` + ``GET /fleet/flows/<flow>``
(serve/restapi.py), the website's Prometheus exposition
(``render_fleet_prometheus``), the SPA fleet tab, and
``python -m data_accelerator_tpu.obs fleet``.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from ..constants import MetricName
from .histogram import HistogramRegistry, LatencyHistogram
from .store import MetricStore

logger = logging.getLogger(__name__)

# delivery-conservation audit code registry (documented in
# OBSERVABILITY.md "Delivery-conservation audit (DX54x)")
AUDIT_CODES: Dict[str, str] = {
    "DX540": "delivery-loss",
    "DX541": "delivery-duplication",
    "DX542": "stale-replica",
}

# a frame must carry these to be aggregatable at all; anything less is
# a corrupt frame (skip-and-count)
_REQUIRED_FRAME_FIELDS = ("flow", "replica", "window", "counters")


class _ReplicaState:
    """Everything the view has folded in from one replica's frames."""

    def __init__(self, replica: str):
        self.replica = replica
        self.replica_index = 1
        self.replica_count = 1
        self.windows: List[int] = []
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.histograms: Dict[str, LatencyHistogram] = {}
        self.ingested: Dict[str, float] = {}
        self.emitted: Dict[str, float] = {}
        self.offsets: Dict[str, List] = {}
        self.health: Optional[dict] = None
        self.alerts: List[dict] = []
        self.batches = 0
        self.last_published_ms = 0
        self.last_window_s = 10.0
        self.final = False
        self.first_seen_ms: Optional[int] = None

    def fold(self, frame: dict) -> None:
        self.replica_index = int(frame.get("replicaIndex") or 1)
        self.replica_count = int(frame.get("replicaCount") or 1)
        self.windows.append(int(frame["window"]))
        for k, v in (frame.get("counters") or {}).items():
            self.counters[k] = self.counters.get(k, 0.0) + float(v)
        for k, v in (frame.get("gauges") or {}).items():
            self.gauges[k] = float(v)
        # histograms ship as cumulative state: the LATEST frame's copy
        # supersedes earlier ones (no double counting across windows)
        for stage, state in (frame.get("histograms") or {}).items():
            self.histograms[stage] = LatencyHistogram.from_state(state)
        delivery = frame.get("delivery") or {}
        for src, n in (delivery.get("ingested") or {}).items():
            self.ingested[src] = self.ingested.get(src, 0.0) + float(n)
        for out, n in (delivery.get("emitted") or {}).items():
            self.emitted[out] = self.emitted.get(out, 0.0) + float(n)
        watermark = frame.get("watermark") or {}
        for key, rng in (watermark.get("offsets") or {}).items():
            cur = self.offsets.get(key)
            if cur is None:
                self.offsets[key] = list(rng)
            else:
                cur[0] = min(cur[0], rng[0])
                cur[1] = max(cur[1], rng[1])
        if frame.get("health") is not None:
            self.health = frame["health"]
        self.alerts = list(frame.get("alerts") or [])
        self.batches += int(frame.get("batches") or 0)
        pub = int(frame.get("publishedAtMs") or 0)
        self.last_published_ms = max(self.last_published_ms, pub)
        if self.first_seen_ms is None:
            self.first_seen_ms = pub
        self.last_window_s = float(frame.get("windowSeconds") or 10.0)
        self.final = self.final or bool(frame.get("final"))

    def status(self, now_ms: float, stale_windows: int) -> str:
        if self.final:
            return "completed"
        horizon_ms = stale_windows * max(self.last_window_s, 1.0) * 1000.0
        if now_ms - self.last_published_ms > horizon_ms:
            return "stale"
        return "live"


class _FleetHealth:
    """Duck-typed health for the fleet AlertEngine's burn-rate rules:
    batch counters summed across the lineage's latest health payloads
    (the same two fields obs/alerts.py samples on a per-process
    HealthState)."""

    def __init__(self):
        self.batches_processed = 0
        self.batches_failed = 0


class FleetView:
    """Aggregates published telemetry frames into fleet-level series."""

    def __init__(
        self,
        client=None,
        url: Optional[str] = None,
        prefix: str = "",
        stale_windows: int = 2,
        rules_fn: Optional[Callable[[str], List[dict]]] = None,
        lineage_fn: Optional[Callable[[str], List[dict]]] = None,
        now_fn=time.time,
    ):
        """``client`` is an ObjectStoreClient (or anything with
        ``list(prefix)``/``get(key)``); ``url`` builds one from an
        ``objstore://host:port/bucket[/prefix]`` reference instead.
        ``lineage_fn(flow)`` optionally supplies job-registry lineage
        records (serve/jobs.py); frames are the fallback source."""
        if client is None:
            if not url:
                raise ValueError("FleetView needs a client or an url")
            from ..compile.aotcache import _parse_objstore_url
            from ..serve.objectstore import ObjectStoreClient

            endpoint, bucket, prefix = _parse_objstore_url(url)
            client = ObjectStoreClient(endpoint, bucket)
        self._client = client
        self._prefix = prefix.strip("/")
        self.stale_windows = int(stale_windows)
        self.rules_fn = rules_fn
        self.lineage_fn = lineage_fn
        self._now = now_fn
        self._lock = threading.Lock()
        self._seen_keys: set = set()
        self._flows: Dict[str, Dict[str, _ReplicaState]] = {}
        # merged surfaces the fleet AlertEngines evaluate over: one
        # MetricStore of per-window delta points and one registry of
        # merged histograms, refreshed on every refresh()
        self.store = MetricStore()
        self.histograms = HistogramRegistry()
        self._health: Dict[str, _FleetHealth] = {}
        self._engines: Dict[str, object] = {}
        self.decode_errors = 0
        self.last_merge_ms = 0.0

    @classmethod
    def from_url(cls, url: str, **kw) -> "FleetView":
        return cls(url=url, **kw)

    # -- ingestion --------------------------------------------------------
    def _list_prefix(self) -> str:
        return f"{self._prefix}/fleet/" if self._prefix else "fleet/"

    def refresh(self) -> int:
        """Pull frames published since the last refresh and fold them
        into the rollup. Returns the number of NEW frames ingested.
        Fail-open everywhere: an unlistable store yields 0 new frames;
        a corrupt frame is skipped and counted."""
        t0 = self._now()
        try:
            keys = sorted(self._client.list(self._list_prefix()))
        except Exception:  # noqa: BLE001 — a flaky store delays, never crashes
            logger.warning("fleet frame listing failed", exc_info=True)
            return 0
        ingested = 0
        for key in keys:
            with self._lock:
                if key in self._seen_keys:
                    continue
                self._seen_keys.add(key)
            if self._ingest_key(key):
                ingested += 1
        if ingested:
            self._rebuild_merged()
        self.last_merge_ms = (self._now() - t0) * 1000.0
        return ingested

    def _ingest_key(self, key: str) -> bool:
        try:
            body = self._client.get(key)
            if body is None:
                raise ValueError("frame vanished between list and get")
            frame = json.loads(body.decode("utf-8"))
            if not isinstance(frame, dict):
                raise ValueError("frame is not an object")
            for field in _REQUIRED_FRAME_FIELDS:
                if field not in frame:
                    raise ValueError(f"frame missing {field!r}")
            if int(frame.get("version") or 0) > FRAME_VERSION_MAX:
                raise ValueError(
                    f"frame version {frame.get('version')} unsupported"
                )
            self.ingest_frame(frame)
            return True
        except Exception as e:  # noqa: BLE001 — skip-and-count, never crash
            with self._lock:
                self.decode_errors += 1
            logger.warning(
                "skipping corrupt telemetry frame %s: %s (%d skipped "
                "so far)", key, e, self.decode_errors,
            )
            return False

    def ingest_frame(self, frame: dict) -> None:
        """Fold one already-decoded frame (tests and the drill call
        this directly; ``refresh`` is the store-backed path)."""
        flow = str(frame["flow"])
        replica = str(frame["replica"])
        with self._lock:
            rep = self._flows.setdefault(flow, {}).setdefault(
                replica, _ReplicaState(replica)
            )
            rep.fold(frame)
        # merged counter series: each frame's windowed deltas land as
        # points under the SAME DATAX-<flow>:<metric> keys a one-box
        # store holds, so fleet alert rules written against per-process
        # series evaluate unchanged over the rollup
        ts = int(
            frame.get("publishedAtMs")
            or (frame.get("watermark") or {}).get("batchTimeMs")
            or self._now() * 1000
        )
        app = MetricName.metric_app_name(flow)
        for metric, value in (frame.get("counters") or {}).items():
            self.store.add_point(f"{app}:{metric}", ts, float(value))
        for metric, value in (frame.get("gauges") or {}).items():
            self.store.add_point(f"{app}:{metric}", ts, float(value))

    def _rebuild_merged(self) -> None:
        """Rebuild the merged histogram registry + fleet health sums
        from the per-replica states (cheap: replicas x stages)."""
        with self._lock:
            flows = {
                flow: list(reps.values())
                for flow, reps in self._flows.items()
            }
        for flow, reps in flows.items():
            stages: Dict[str, LatencyHistogram] = {}
            health = _FleetHealth()
            for rep in reps:
                for stage, hist in rep.histograms.items():
                    cur = stages.get(stage)
                    stages[stage] = (
                        hist if cur is None else cur.merge(hist)
                    )
                if rep.health:
                    health.batches_processed += int(
                        rep.health.get("batchesProcessed") or 0
                    )
                    health.batches_failed += int(
                        rep.health.get("batchesFailed") or 0
                    )
            for stage, merged in stages.items():
                self.histograms.put(flow, stage, merged)
            self._health[flow] = health

    # -- rollup surfaces --------------------------------------------------
    def flows(self) -> List[str]:
        with self._lock:
            return sorted(self._flows)

    def _replicas(self, flow: str) -> List[_ReplicaState]:
        with self._lock:
            return list(self._flows.get(flow, {}).values())

    def fleet_metrics(self, flow: str) -> dict:
        """The merged fleet series for one flow + per-replica
        breakdowns (the ``/fleet/flows/<flow>`` payload)."""
        reps = self._replicas(flow)
        now_ms = self._now() * 1000.0
        counters: Dict[str, float] = {}
        for rep in reps:
            for k, v in rep.counters.items():
                counters[k] = counters.get(k, 0.0) + v
        hist_rollup = {}
        for stage in self.histograms.stages(flow):
            h = self.histograms.get(flow, stage)
            hist_rollup[stage] = {
                "count": h.count,
                "p50": h.percentile(50),
                "p95": h.percentile(95),
                "p99": h.percentile(99),
            }
        statuses = {
            rep.replica: rep.status(now_ms, self.stale_windows)
            for rep in reps
        }
        return {
            "flow": flow,
            "counters": counters,
            "histograms": hist_rollup,
            "replicas": {
                rep.replica: {
                    "status": statuses[rep.replica],
                    "replicaIndex": rep.replica_index,
                    "replicaCount": rep.replica_count,
                    "frames": len(rep.windows),
                    "windows": (
                        [min(rep.windows), max(rep.windows)]
                        if rep.windows else []
                    ),
                    "batches": rep.batches,
                    "lastSeenMs": rep.last_published_ms,
                    "final": rep.final,
                    "counters": dict(rep.counters),
                    "gauges": dict(rep.gauges),
                    "alerts": rep.alerts,
                    "offsets": {
                        k: list(v) for k, v in rep.offsets.items()
                    },
                }
                for rep in reps
            },
            "staleReplicas": sorted(
                r for r, s in statuses.items() if s == "stale"
            ),
            "alerts": self.evaluate_alerts(flow),
            "lineage": self.lineage(flow),
            "audit": self.audit(flow),
        }

    def summary(self) -> dict:
        """The ``/fleet/metrics`` payload: every flow's rollup plus
        aggregator self-stats."""
        return {
            "flows": {f: self.fleet_metrics(f) for f in self.flows()},
            "decodeErrors": self.decode_errors,
            "mergeMs": round(self.last_merge_ms, 3),
        }

    # -- fleet-scope alerts ----------------------------------------------
    def evaluate_alerts(self, flow: str) -> List[dict]:
        """Evaluate the flow's alert rules — the SAME rule dicts the
        per-process engines run (obs/alerts.py) — over the merged
        store/histograms/health. Burn-rate/SLO rules therefore compute
        error-budget burn on fleet totals."""
        from .alerts import AlertEngine, default_rules

        engine = self._engines.get(flow)
        if engine is None:
            rules = (
                self.rules_fn(flow) if self.rules_fn is not None
                else default_rules(flow)
            )
            engine = AlertEngine(
                rules,
                flow=flow,
                store=self.store,
                histograms=self.histograms,
                health=self._health.setdefault(flow, _FleetHealth()),
                now_fn=self._now,
            )
            self._engines[flow] = engine
        else:
            # health object identity must track the latest rebuild
            engine.health = self._health.get(flow, engine.health)
        try:
            return engine.evaluate()
        except Exception:  # noqa: BLE001 — alert evaluation is advisory
            logger.exception("fleet alert evaluation failed for %s", flow)
            return []

    # -- lineage ----------------------------------------------------------
    def lineage(self, flow: str) -> List[dict]:
        """The flow's replica succession, oldest first. Job-registry
        records win when a ``lineage_fn`` is wired (they carry the
        authoritative ``statePartitionMap``); frames are the fallback
        — ordered by first publication, which tracks generation order
        across a rescale handoff."""
        if self.lineage_fn is not None:
            try:
                records = self.lineage_fn(flow)
                if records:
                    return records
            except Exception:  # noqa: BLE001 — registry outage falls back
                logger.warning(
                    "lineage_fn failed for %s; deriving lineage from "
                    "frames", flow, exc_info=True,
                )
        reps = sorted(
            self._replicas(flow), key=lambda r: (r.first_seen_ms or 0)
        )
        now_ms = self._now() * 1000.0
        return [
            {
                "replica": rep.replica,
                "replicaIndex": rep.replica_index,
                "replicaCount": rep.replica_count,
                "firstSeenMs": rep.first_seen_ms,
                "lastSeenMs": rep.last_published_ms,
                "status": rep.status(now_ms, self.stale_windows),
            }
            for rep in reps
        ]

    # -- delivery-conservation audit (DX54x) ------------------------------
    def audit(self, flow: str, output: Optional[str] = None) -> dict:
        """Check Σ ingested == Σ emitted across the flow's lineage and
        flag stale replicas. Returns at most ONE DX540-or-DX541 event
        per flow (loss and duplication are mutually exclusive on the
        same totals) and one DX542 per stale replica — repeated audits
        of the same state yield the same events, so "fires exactly
        once" holds by construction."""
        reps = self._replicas(flow)
        now_ms = self._now() * 1000.0
        total_ingested = 0.0
        emitted_by_output: Dict[str, float] = {}
        for rep in reps:
            total_ingested += sum(rep.ingested.values())
            for out, n in rep.emitted.items():
                emitted_by_output[out] = emitted_by_output.get(out, 0.0) + n
        if output is None and emitted_by_output:
            # aggregating outputs (windowed GROUP BYs) under-emit by
            # construction; the passthrough output — the one that
            # conserves — has the highest emitted total
            output = max(emitted_by_output, key=emitted_by_output.get)
        total_emitted = emitted_by_output.get(output or "", 0.0)
        events: List[dict] = []
        if reps and total_ingested > total_emitted:
            events.append({
                "code": "DX540",
                "name": AUDIT_CODES["DX540"],
                "flow": flow,
                "output": output,
                "ingested": total_ingested,
                "emitted": total_emitted,
                "message": (
                    f"delivery loss on {flow}/{output}: "
                    f"{total_ingested:.0f} ingested vs "
                    f"{total_emitted:.0f} emitted across the lineage"
                ),
            })
        elif reps and total_emitted > total_ingested:
            events.append({
                "code": "DX541",
                "name": AUDIT_CODES["DX541"],
                "flow": flow,
                "output": output,
                "ingested": total_ingested,
                "emitted": total_emitted,
                "message": (
                    f"delivery duplication on {flow}/{output}: "
                    f"{total_emitted:.0f} emitted vs "
                    f"{total_ingested:.0f} ingested across the lineage"
                ),
            })
        for rep in reps:
            if rep.status(now_ms, self.stale_windows) == "stale":
                events.append({
                    "code": "DX542",
                    "name": AUDIT_CODES["DX542"],
                    "flow": flow,
                    "replica": rep.replica,
                    "message": (
                        f"replica {rep.replica} of {flow} went quiet "
                        f"without its final drain frame — its in-flight "
                        f"window is unaccounted"
                    ),
                })
        counts = {code: 0 for code in AUDIT_CODES}
        for ev in events:
            counts[ev["code"]] += 1
        return {
            "flow": flow,
            "output": output,
            "ingested": total_ingested,
            "emitted": emitted_by_output,
            "conserved": not any(
                e["code"] in ("DX540", "DX541") for e in events
            ),
            "events": events,
            "counts": counts,
        }


# newest frame schema this aggregator understands (frames from a newer
# publisher are skip-and-count, not a crash)
FRAME_VERSION_MAX = 1


def render_fleet_prometheus(view: FleetView) -> str:
    """The fleet rollup as Prometheus text — appended to the website's
    ``/metrics`` exposition beside the per-process families
    (obs/exposition.py render_prometheus)."""
    out: List[str] = []
    out.append("# TYPE datax_fleet_metric_total gauge")
    for flow in view.flows():
        fm = view.fleet_metrics(flow)
        for metric, value in sorted(fm["counters"].items()):
            out.append(
                f'datax_fleet_metric_total{{flow="{flow}",'
                f'metric="{metric}"}} {value}'
            )
    out.append("# TYPE datax_fleet_replicas gauge")
    for flow in view.flows():
        fm = view.fleet_metrics(flow)
        by_status: Dict[str, int] = {}
        for rep in fm["replicas"].values():
            by_status[rep["status"]] = by_status.get(rep["status"], 0) + 1
        for status, n in sorted(by_status.items()):
            out.append(
                f'datax_fleet_replicas{{flow="{flow}",'
                f'status="{status}"}} {n}'
            )
    out.append("# TYPE datax_fleet_stage_latency_ms summary")
    for flow in view.flows():
        for stage in view.histograms.stages(flow):
            h = view.histograms.get(flow, stage)
            for q in (50, 95, 99):
                v = h.percentile(q)
                if v is not None:
                    out.append(
                        f'datax_fleet_stage_latency_ms{{flow="{flow}",'
                        f'stage="{stage}",quantile="0.{q}"}} {v:.3f}'
                    )
    out.append("# TYPE datax_fleet_frame_decode_errors_total counter")
    out.append(
        f"datax_fleet_frame_decode_errors_total {view.decode_errors}"
    )
    out.append("# TYPE datax_fleet_audit_events gauge")
    for flow in view.flows():
        audit = view.audit(flow)
        for code, n in sorted(audit["counts"].items()):
            out.append(
                f'datax_fleet_audit_events{{flow="{flow}",'
                f'code="{code}"}} {n}'
            )
    return "\n".join(out) + "\n"
