"""On-demand jax profiler surface: ``POST /profile?seconds=N``.

Replaces the old first-N-batches trace dump (a conf key you had to set
BEFORE starting the host, which is never when the mystery happens):
a live host now arms ``jax.profiler`` on demand through its
observability port, captures for N seconds while batches keep flowing,
and the capture lands beside the flight recorder —

- ``POST <host>/profile?seconds=N`` (obs/exposition.py) starts a
  capture and returns its path immediately; a timer thread stops the
  trace when the window closes.
- every finished capture is drained by the streaming host at the next
  batch finish and recorded as a ``profiler/capture`` span inside that
  batch's trace (so ``obs trace <batch>`` shows exactly which capture
  overlapped which batches) and counted by the
  ``Profiler_Captures_Count`` registry series.
- ``python -m data_accelerator_tpu.obs profile <url>`` drives it from
  a terminal; captures open in tensorboard/xprof.

No-op posture: on a backend/build without ``jax.profiler`` the surface
reports unavailable, the endpoint answers 501, and nothing else
changes — profiling is diagnostics, never load-bearing.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

DEFAULT_SECONDS = 5.0
MAX_SECONDS = 120.0


def profiler_available() -> bool:
    """True when this process can start a jax profiler trace."""
    try:
        import jax.profiler  # noqa: F401

        return hasattr(jax.profiler, "start_trace")
    except Exception:  # noqa: BLE001 — any import failure = unavailable
        return False


class ProfilerSurface:
    """One host's on-demand capture state: at most one trace at a time,
    a timer to close the window, and a drain queue of finished captures
    for the host to stitch into batch traces."""

    def __init__(self, base_dir: str, flow: str = ""):
        self.base_dir = base_dir
        self.flow = flow
        self.captures_count = 0
        self._seq = 0
        self._active: Optional[dict] = None
        self._timer: Optional[threading.Timer] = None
        self._finished: List[Dict] = []
        self._lock = threading.Lock()

    @property
    def available(self) -> bool:
        return profiler_available()

    def active(self) -> Optional[dict]:
        with self._lock:
            return dict(self._active) if self._active else None

    def start(self, seconds: float = DEFAULT_SECONDS) -> dict:
        """Arm a capture for ``seconds``; returns
        ``{path, seconds, active}`` or ``{error}`` (already capturing /
        profiler unavailable). The path is returned immediately so the
        caller can watch it fill."""
        seconds = min(max(float(seconds), 0.1), MAX_SECONDS)
        if not self.available:
            return {"error": "jax.profiler unavailable on this backend"}
        with self._lock:
            if self._active is not None:
                return {
                    "error": "capture already in progress",
                    "path": self._active["path"],
                }
            self._seq += 1
            path = os.path.join(
                self.base_dir, f"capture-{self._seq:04d}"
            )
            os.makedirs(path, exist_ok=True)
            import jax

            try:
                jax.profiler.start_trace(path)
            except Exception as e:  # noqa: BLE001 — diagnostics only
                logger.warning("profiler start failed: %s", e)
                return {"error": f"profiler start failed: {e}"}
            self._active = {
                "path": path,
                "seconds": seconds,
                "startedTs": time.time(),
            }
            self._timer = threading.Timer(seconds, self._stop_timed)
            self._timer.daemon = True
            self._timer.start()
            logger.info(
                "profiler capture armed for %.1fs -> %s", seconds, path
            )
            return {"path": path, "seconds": seconds, "active": True}

    def _stop_timed(self) -> None:
        try:
            self.stop()
        except Exception:  # noqa: BLE001 — timer thread must not die loud
            logger.exception("timed profiler stop failed")

    def stop(self) -> Optional[str]:
        """Close the active capture (idempotent); returns its path."""
        with self._lock:
            active, self._active = self._active, None
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        if active is None:
            return None
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — capture may be torn
            logger.warning("profiler stop failed: %s", e)
        active["durationMs"] = round(
            (time.time() - active["startedTs"]) * 1000.0, 1
        )
        with self._lock:
            self.captures_count += 1
            self._finished.append(active)
        logger.info("profiler capture written to %s", active["path"])
        return active["path"]

    def drain_finished(self) -> List[Dict]:
        """Captures completed since the last drain — the host records
        each as a ``profiler/capture`` span event on the batch trace
        that drains it."""
        with self._lock:
            out, self._finished = self._finished, []
            return out
