"""Metrics ingestor side-car: metric event stream -> metric store.

reference: Services/DataX.Metrics/DataX.Metrics.Ingestor — a stateless
service running an EventProcessorHost over the metrics EventHub
(Ingestor.cs:108-150); each event body is newline-split, each line parsed
into ``{app, metric, uts, value}`` and written to a Redis sorted set
keyed ``app:metric`` scored by epoch millis
(IngestorEventProcessor.cs:92-96,141). Bad lines are logged and skipped,
never failing the batch.

TPU-native stand-in: the metric stream is newline-delimited JSON over
TCP (the same DCN wire format the engine's StreamSink speaks), consumed
by an acceptor thread per connection — connection-per-producer plays the
role of EventProcessorHost's partition leases (each producer's stream is
owned by exactly one reader thread). Rows land in a MetricStore
(obs/store.py, the Redis analog) that the dashboard feed reads.

The producer side is ``MetricStreamSender`` — plugged into
MetricLogger's ``eventhub_sender`` hook so a job emits metrics over the
wire exactly like the reference's EventHub metric sink
(MetricLogger.scala:60-63).
"""

from __future__ import annotations

import json
import logging
import socket
import threading
from typing import Optional

from .store import METRIC_STORE, MetricStore

logger = logging.getLogger(__name__)


class MetricsIngestor:
    """TCP server ingesting metric JSON lines into a MetricStore."""

    def __init__(
        self,
        store: Optional[MetricStore] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.store = store if store is not None else METRIC_STORE
        self.messages_received = 0
        self.metrics_sent = 0
        self.parse_errors = 0
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(8)
        self.port = self._server.getsockname()[1]
        self._closing = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            # one reader per producer connection — the partition-lease
            # analog: a producer's ordered stream has a single owner
            threading.Thread(target=self._reader, args=(conn,), daemon=True).start()

    def _reader(self, conn) -> None:
        with conn:
            f = conn.makefile("rb")
            for line in f:
                line = line.strip()
                if not line:
                    continue
                self.messages_received += 1
                self.ingest_line(line.decode("utf-8", errors="replace"))

    def ingest_line(self, line: str) -> bool:
        """Parse one metric line and store it; bad lines are counted and
        skipped (GenerateRow's per-line try/catch)."""
        try:
            item = json.loads(line)
            app = item["app"]
            metric = item["metric"]
            uts = int(item.get("uts") or item.get("EventTime"))
            value = item["value"]
        except (ValueError, KeyError, TypeError) as e:
            self.parse_errors += 1
            logger.warning("bad metric line %r: %s", line[:200], e)
            return False
        key = f"{app}:{metric}" if not metric.startswith(app) else metric
        self.store.add_point(key, uts, value)
        self.metrics_sent += 1
        return True

    def close(self) -> None:
        self._closing = True
        try:
            self._server.close()
        except OSError:
            pass

    # lifecycle alias so service composition can stop() every part
    stop = close


class MetricStreamSender:
    """Producer half: ships metric points over TCP to the ingestor.

    Callable with ``(key, uts_ms, value)`` so it plugs straight into
    MetricLogger's ``eventhub_sender`` hook. The key arrives already
    namespaced (``DATAX-<flow>:<metric>``); it is split back into
    app/metric like the reference's metric JSON carries both fields.
    """

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.addr = (host, port)
        self.timeout_s = timeout_s
        self._sock = None
        self._lock = threading.Lock()

    def _connect(self):
        return socket.create_connection(self.addr, timeout=self.timeout_s)

    def __call__(self, key: str, uts_ms: int, value) -> None:
        app, _, metric = key.partition(":")
        payload = json.dumps(
            {"app": app, "metric": metric, "uts": int(uts_ms), "value": value},
            default=str,
        ).encode() + b"\n"
        with self._lock:
            try:
                if self._sock is None:
                    self._sock = self._connect()
                self._sock.sendall(payload)
            except OSError as e:
                # metrics never fail the batch: drop after one reconnect try
                try:
                    if self._sock is not None:
                        self._sock.close()
                    self._sock = self._connect()
                    self._sock.sendall(payload)
                except OSError:
                    self._sock = None
                    logger.warning("metric send to %s failed: %s", self.addr, e)

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
