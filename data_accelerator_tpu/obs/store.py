"""In-process metric store: sorted sets of (timestamp, payload) per key.

Plays the role Redis plays for the reference (metrics written with
``zadd app:metric {uts,val}`` — MetricLogger.scala:20-24 and
IngestorEventProcessor.cs:92-96,141 — and read back by the dashboard via
``zrangebyscore`` — redisProxy.js:21-52). The API mirrors the sorted-set
subset used so a real Redis can be swapped in behind the same calls.
"""

from __future__ import annotations

import bisect
import json
import threading
from typing import Dict, List, Optional, Tuple


class MetricStore:
    def __init__(self):
        self._lock = threading.Lock()
        # key -> sorted list of (score, member)
        self._sets: Dict[str, List[Tuple[float, str]]] = {}
        self._listeners: List = []

    # -- redis-like sorted set ops --------------------------------------
    def zadd(self, key: str, score: float, member: str) -> None:
        with self._lock:
            entries = self._sets.setdefault(key, [])
            bisect.insort(entries, (score, member))
        for fn in list(self._listeners):
            try:
                fn(key, score, member)
            except Exception:
                pass

    def zrangebyscore(
        self, key: str, lo: float, hi: float
    ) -> List[Tuple[float, str]]:
        with self._lock:
            entries = self._sets.get(key, [])
            i = bisect.bisect_left(entries, (lo, ""))
            j = bisect.bisect_right(entries, (hi, "￿"))
            return entries[i:j]

    def zcard(self, key: str) -> int:
        with self._lock:
            return len(self._sets.get(key, []))

    def keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return [k for k in self._sets if k.startswith(prefix)]

    def clear(self) -> None:
        with self._lock:
            self._sets.clear()

    # -- push feed (socket.io analog for the dashboard) ------------------
    def subscribe(self, fn) -> None:
        """fn(key, score, member) called on every zadd (dashboard push —
        the analog of redisProxy.js polling + socket.io 'datapoints')."""
        self._listeners.append(fn)

    def unsubscribe(self, fn) -> None:
        if fn in self._listeners:
            self._listeners.remove(fn)

    # -- convenience -----------------------------------------------------
    def add_point(self, key: str, uts_ms: int, value) -> None:
        self.zadd(key, float(uts_ms), json.dumps({"uts": uts_ms, "val": value}))

    def points(self, key: str, lo_ms: float = 0, hi_ms: float = float("inf")):
        return [json.loads(m) for _, m in self.zrangebyscore(key, lo_ms, hi_ms)]


# the one-box process-wide store (DeploymentLocal's Redis analog)
METRIC_STORE = MetricStore()
