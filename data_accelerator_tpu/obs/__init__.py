"""Observability: metric store, metric logger, telemetry."""

from .store import MetricStore, METRIC_STORE
from .metrics import MetricLogger

__all__ = ["MetricStore", "METRIC_STORE", "MetricLogger"]
