"""Observability: metric store, metric logger, telemetry, tracing,
latency histograms, Prometheus/health exposition."""

from .store import MetricStore, METRIC_STORE
from .metrics import MetricLogger
from .histogram import HISTOGRAMS, HistogramRegistry, LatencyHistogram
from .tracing import Tracer, current_trace, span
from .exposition import HealthState, ObservabilityServer, render_prometheus

__all__ = [
    "MetricStore",
    "METRIC_STORE",
    "MetricLogger",
    "HISTOGRAMS",
    "HistogramRegistry",
    "LatencyHistogram",
    "Tracer",
    "current_trace",
    "span",
    "HealthState",
    "ObservabilityServer",
    "render_prometheus",
]
