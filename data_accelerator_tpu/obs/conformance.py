"""Model-vs-observed conformance: runtime drift detection (DX5xx).

The static analysis tiers predict what a deployed flow will cost — the
DX2xx device-plan model is byte-exact against the XLA lowering
(``analysis/costmodel.py``), and the fleet placer admits jobs on those
numbers. Nothing until now checked the *running* job against them.
Config generation embeds the flow's machine-readable cost-model report
into the generated conf (``datax.job.process.conformance.model``, a
compact JSON produced by ``DevicePlanReport.runtime_model()``); at
runtime a ``ConformanceMonitor`` on each host compares windowed
observations — ``Transfer_D2HBytes``, per-output occupancy, retrace
counts — against those predictions and exports:

- ``Conformance_*`` gauges (observed/predicted ratios, merged into the
  per-batch metric dict so they ride the normal store/Prometheus/SPA
  path), and
- typed **drift events** into the flight recorder and metric store:

  | code | name | meaning |
  |---|---|---|
  | DX501 | d2h-bytes-drift | windowed observed D2H bytes exceed the modeled per-batch transfer by more than the tolerance band |
  | DX502 | occupancy-vs-modeled-cardinality | an output's observed row occupancy exceeds the modeled group/join cardinality — the capacity planning input was wrong |
  | DX503 | unmodeled-retrace | the jitted step re-traced after warmup; steady state is modeled as trace-free |
  | DX510 | ici-bytes-drift | windowed observed mesh collective bytes (``Mesh_ICI_Bytes``) exceed the DX7xx sharding model's wire prediction by more than the tolerance band |
  | DX511 | mesh-collective-count-drift | the executed mesh program's collective-op census (``Mesh_Reshard_Count``) changed from its post-warmup baseline — a re-trace repartitioned the step |
  | DX520 | stage-time-drift | a stage's observed latency p50 exceeds the calibrated roofline prediction (``max(bytes/BW, flops/F) + dispatch overhead`` over the measured machine profile, obs/calibrate.py) by more than the band |
  | DX521 | dispatch-overhead-dominated | DX520's condition on a stage whose *model* is all fixed dispatch overhead — the slowdown is per-dispatch cost, not data movement |
  | DX522 | hbm-footprint-drift | live HBM peak (``Hbm_PeakBytes``, the per-window ``memory_stats`` sample) drifted above the DX2xx modeled footprint band |

The DX52x trio is the *time* half of the loop (PR 12): S620 embeds the
byte+FLOP closed forms; the host calibrates its own machine profile at
init (``obs/calibrate.py``) and prices them into per-stage roofline
milliseconds (``ConformanceModel.latency_predictions``), which the
monitor judges against the same windowed ``Latency-<Stage>-p50``
histogram series the dashboards read.

The DX51x pair is the runtime half of the mesh tier
(``analysis/meshcheck.py``): config generation embeds the sharding
plan's collective model into mesh jobs' confs
(``datax.job.process.mesh.model``, the S660 stage), the mesh processor
censuses its own compiled program's collectives per batch
(``dist/mesh.py collective_summary`` -> ``Mesh_ICI_Bytes`` /
``Mesh_Reshard_Count``), and this monitor judges one against the
other. The model charges the planned-layout gathers; the partitioner
is free to do better (or trade all-gathers for all-reduce chains), so
the DX510 band is wider than DX501's — it catches the model *missing*
traffic wholesale, not micro-divergence.

Events fire on the *transition* into drift (and re-arm on recovery), so
a sustained drift is one event, not one per batch; the cumulative
``Conformance_Drift_Count`` gauge keeps the total visible. This is the
observability substrate ROADMAP item 5's controller reads: you cannot
act on drift you cannot see.

Device-resident result path note: with background transfer
(``process.pipeline.backgroundtransfer``) ``observe()`` is called from
the host's landing thread, one call per batch finish in strict FIFO
order — the windowed series it judges (``Transfer_D2HBytes``, which
includes the counts vector's ``Sync_CountsBytes``, per-output
occupancy, retraces) are unchanged by the split, and the modeled
``d2hBytesPerBatch`` it compares against stays a wire-bytes term (the
donated output-slot HBM lives in the model's ``hbmBytes``, not here).
"""

from __future__ import annotations

import json
import logging
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..constants import MetricName

logger = logging.getLogger(__name__)

# runtime drift code registry (documented in OBSERVABILITY.md
# "Conformance monitoring (DX5xx)")
DRIFT_CODES: Dict[str, str] = {
    "DX501": "d2h-bytes-drift",
    "DX502": "occupancy-vs-modeled-cardinality",
    "DX503": "unmodeled-retrace",
    "DX510": "ici-bytes-drift",
    "DX511": "mesh-collective-count-drift",
    "DX520": "stage-time-drift",
    "DX521": "dispatch-overhead-dominated",
    "DX522": "hbm-footprint-drift",
}

# observed/predicted ratio above which DX501 fires (sized transfer makes
# observed < predicted the healthy direction; exceeding the model means
# the model missed traffic)
DEFAULT_D2H_RATIO_HIGH = 1.5
# observed/predicted ratio above which DX510 fires. The DX7xx model
# prices the planned-layout gathers; GSPMD legitimately trades them for
# partial-aggregation all-reduce chains whose ring wire cost runs up to
# ~4x the gather model on the join-heavy MULTICHIP flow (measured; the
# dryrun asserts it), so the band is much wider than DX501's — it
# catches wholesale model misses (an unmodeled reshard storm, a
# dictionary-growth retrace multiplying the collective census), not
# partitioner microstructure. DX511's count-drift check is the sharp
# instrument for repartitioning.
DEFAULT_ICI_RATIO_HIGH = 8.0
# observed rows / modeled cardinality above which DX502 fires
DEFAULT_OCCUPANCY_FACTOR = 2.0
# observed p50 / predicted roofline ms above which DX520 fires. The
# latency closed forms are LOWER bounds (peak bandwidth, peak dense
# FLOP/s — analysis/costmodel.py stage_time_ms); achieved efficiency
# on gather/sort-heavy SQL stages legitimately runs several-fold under
# peak, so like DX510 the band is wide: it catches a stage going
# wholesale slow (bandwidth regression, dispatch-overhead domination,
# an HBM re-layout), not roofline optimism.
DEFAULT_STAGE_TIME_RATIO_HIGH = 10.0
# predicted ms below which DX520/DX521 decline to judge a stage: a
# sub-millisecond roofline prediction means fixed host-side costs the
# device model deliberately does not cover (row materialization, GIL
# scheduling, tunnel RTT) dominate the observation, and any ratio
# against it is noise, not drift — the missing-prediction posture
# (silence) applies. An explicit conformance.latency PIN is always
# judged: the operator asserted the number.
DEFAULT_STAGE_TIME_FLOOR_MS = 1.0
# observed live HBM peak / the DX2xx modeled footprint above which
# DX522 fires — the byte model is exact (tier-1 asserts model ==
# lowering), so the band only needs to absorb allocator slack and
# jax runtime scratch, not model error
DEFAULT_HBM_RATIO_HIGH = 1.5
# windowed samples required before ratios are judged (and before a
# retrace counts as unmodeled — the first trace IS the model)
DEFAULT_WARMUP_BATCHES = 4
DEFAULT_WINDOW = 16


@dataclass
class DriftEvent:
    """One typed model-vs-observed drift detection."""

    code: str
    metric: str
    observed: float
    predicted: float
    ratio: float
    batch_time_ms: Optional[int] = None
    message: str = ""

    def to_props(self) -> dict:
        return {
            "code": self.code,
            "name": DRIFT_CODES.get(self.code, self.code),
            "metric": self.metric,
            "observed": round(self.observed, 2),
            "predicted": round(self.predicted, 2),
            "ratio": round(self.ratio, 4),
            "batchTime": self.batch_time_ms,
            "message": self.message,
        }


@dataclass
class ConformanceModel:
    """The embedded slice of the DX2xx cost report a running host can
    check itself against. All fields optional — a missing prediction
    simply disables its checks (the missing-prediction posture is
    silence, not failure)."""

    d2h_bytes_per_batch: Optional[float] = None
    hbm_bytes: Optional[float] = None
    # modeled FLOPs/batch across all stages (the compute side of the
    # DX520 roofline prediction)
    flops: Optional[float] = None
    # output dataset -> {"rows": modeled cardinality, "capacity": padded}
    outputs: Dict[str, dict] = field(default_factory=dict)
    # per-stage hbmBytes/d2hBytes/flops (the DX520 latency inputs; the
    # CLI/SPA also render it)
    stages: List[dict] = field(default_factory=list)
    # mesh sharding-plan predictions (datax.job.process.mesh.model, the
    # DX7xx analyzer's runtime artifact): modeled collective wire bytes
    # per batch and the planned reshard count — the DX510/DX511 inputs
    ici_wire_bytes_per_batch: Optional[float] = None
    reshard_count: Optional[float] = None

    @classmethod
    def from_json(
        cls, text: str, mesh_text: Optional[str] = None,
    ) -> Optional["ConformanceModel"]:
        obj: Optional[dict] = None
        if text:
            try:
                parsed = json.loads(text)
                obj = parsed if isinstance(parsed, dict) else None
            except ValueError:
                logger.warning("unparseable conformance model; monitor off")
                obj = None
        mesh_totals: dict = {}
        if mesh_text:
            try:
                mesh_obj = json.loads(mesh_text)
                if isinstance(mesh_obj, dict):
                    mesh_totals = mesh_obj.get("totals") or {}
            except ValueError:
                logger.warning("unparseable mesh model; DX51x checks off")
        if obj is None and not mesh_totals:
            return None
        obj = obj or {}
        totals = obj.get("totals") or {}
        return cls(
            d2h_bytes_per_batch=totals.get("d2hBytesPerBatch"),
            hbm_bytes=totals.get("hbmBytes"),
            flops=totals.get("flops"),
            outputs={
                k: v for k, v in (obj.get("outputs") or {}).items()
                if isinstance(v, dict)
            },
            stages=list(obj.get("stages") or []),
            ici_wire_bytes_per_batch=mesh_totals.get("iciWireBytesPerBatch"),
            reshard_count=mesh_totals.get("reshardCount"),
        )

    @classmethod
    def from_conf(cls, dict_) -> Optional["ConformanceModel"]:
        raw = dict_.get_sub_dictionary(
            "datax.job.process.conformance."
        ).get("model")
        mesh_raw = dict_.get_sub_dictionary(
            "datax.job.process.mesh."
        ).get("model")
        if not raw and not mesh_raw:
            return None
        return cls.from_json(raw or "", mesh_raw)

    def latency_predictions(self, profile: dict) -> tuple:
        """The DX520 comparison baseline: roofline per-stage latency
        under ``profile`` (a calibrated ``MachineProfile.to_dict()``).
        Bytes and FLOPs travel in the conf-embedded model; this turns
        them into milliseconds on the machine that will be judged.
        Returns ``(predictions, compute_ms, overhead_ms)`` —
        predictions keyed by runtime histogram stage, and the model's
        compute-vs-dispatch-overhead split (the DX521 input: a stage
        whose predicted time is all fixed overhead has nothing to gain
        from bandwidth, only from batching/fusing dispatches)."""
        from ..analysis.costmodel import (
            latency_model,
            stage_latency_predictions,
        )

        model = latency_model(
            self.stages,
            {
                "d2hBytesPerBatch": self.d2h_bytes_per_batch,
                "flops": self.flops,
            },
            profile,
            profile_source="calibrated",
        )
        totals = model["totals"]
        return (
            stage_latency_predictions(model),
            float(totals["computeMs"]),
            float(totals["dispatchOverheadMs"]),
        )


class ConformanceMonitor:
    """Windowed model-vs-observed comparison, fed once per batch finish
    with the batch's metric dict (``FlowProcessor`` collect output plus
    the host's additions). Returns gauges to merge into the same dict
    and the drift events that fired this batch."""

    def __init__(
        self,
        model: ConformanceModel,
        flow: str = "",
        window: int = DEFAULT_WINDOW,
        warmup: int = DEFAULT_WARMUP_BATCHES,
        d2h_ratio_high: float = DEFAULT_D2H_RATIO_HIGH,
        occupancy_factor: float = DEFAULT_OCCUPANCY_FACTOR,
        ici_ratio_high: float = DEFAULT_ICI_RATIO_HIGH,
        stage_time_ratio_high: float = DEFAULT_STAGE_TIME_RATIO_HIGH,
        stage_time_floor_ms: float = DEFAULT_STAGE_TIME_FLOOR_MS,
        hbm_ratio_high: float = DEFAULT_HBM_RATIO_HIGH,
    ):
        self.model = model
        self.flow = flow
        self.window = max(1, int(window))
        self.warmup = max(1, int(warmup))
        self.d2h_ratio_high = float(d2h_ratio_high)
        self.occupancy_factor = float(occupancy_factor)
        self.ici_ratio_high = float(ici_ratio_high)
        self.stage_time_ratio_high = float(stage_time_ratio_high)
        self.stage_time_floor_ms = float(stage_time_floor_ms)
        self.hbm_ratio_high = float(hbm_ratio_high)
        # DX520/DX521 state: runtime-stage -> predicted roofline ms,
        # set by set_latency() once the host has a calibrated profile
        # (or pinned from the conf's conformance.latency override);
        # the compute/overhead split routes drift to DX521 when the
        # model says the stage is all fixed dispatch cost
        self.latency: Dict[str, float] = {}
        self.latency_pinned = False
        self._latency_compute_ms = 0.0
        self._latency_overhead_ms = 0.0
        self.batches = 0
        self.drift_count = 0
        self._d2h: deque = deque(maxlen=self.window)
        self._ici: deque = deque(maxlen=self.window)
        self._hbm: deque = deque(maxlen=self.window)
        # the executed mesh program's first post-warmup collective-op
        # count — DX511's self-baseline (a change means a re-trace
        # repartitioned the step)
        self._collective_baseline: Optional[float] = None
        self._occupancy: Dict[str, deque] = {}
        # codes (keyed per metric) currently in drift — events fire on
        # the transition in, re-arm on recovery
        self._active: set = set()

    @classmethod
    def from_conf(cls, dict_, flow: str = "") -> Optional["ConformanceMonitor"]:
        model = ConformanceModel.from_conf(dict_)
        sub = dict_.get_sub_dictionary("datax.job.process.conformance.")
        # operator latency pin: conformance.latency = JSON stage->ms
        # replaces the computed roofline predictions outright (the
        # injected-slowdown acceptance drill uses the same door)
        pin: Optional[Dict[str, float]] = None
        lat_raw = sub.get("latency")
        if lat_raw:
            try:
                parsed = json.loads(lat_raw)
                if isinstance(parsed, dict):
                    pin = {
                        str(k): float(v) for k, v in parsed.items()
                        if isinstance(v, (int, float))
                    }
            except ValueError:
                logger.warning(
                    "unparseable conformance.latency pin; ignored"
                )
        if model is None:
            # a valid pin alone arms the monitor (DX520/521 only) —
            # the operator asserted the numbers, no byte model needed
            if not pin:
                return None
            model = ConformanceModel()
        window = sub.get_int_option("window")
        warmup = sub.get_int_option("warmup")
        high = sub.get_double_option("d2hratiohigh")
        occ = sub.get_double_option("occupancyfactor")
        ici = sub.get_double_option("iciratiohigh")
        stage_t = sub.get_double_option("stagetimeratiohigh")
        stage_floor = sub.get_double_option("stagetimefloorms")
        hbm = sub.get_double_option("hbmratiohigh")
        mon = cls(
            model,
            flow=flow,
            window=window if window is not None else DEFAULT_WINDOW,
            warmup=warmup if warmup is not None else DEFAULT_WARMUP_BATCHES,
            d2h_ratio_high=(
                high if high is not None else DEFAULT_D2H_RATIO_HIGH
            ),
            occupancy_factor=(
                occ if occ is not None else DEFAULT_OCCUPANCY_FACTOR
            ),
            ici_ratio_high=(
                ici if ici is not None else DEFAULT_ICI_RATIO_HIGH
            ),
            stage_time_ratio_high=(
                stage_t if stage_t is not None
                else DEFAULT_STAGE_TIME_RATIO_HIGH
            ),
            stage_time_floor_ms=(
                stage_floor if stage_floor is not None
                else DEFAULT_STAGE_TIME_FLOOR_MS
            ),
            hbm_ratio_high=(
                hbm if hbm is not None else DEFAULT_HBM_RATIO_HIGH
            ),
        )
        if pin:
            mon.set_latency(pin, pinned=True)
        return mon

    def set_latency(
        self,
        predictions: Dict[str, float],
        compute_ms: float = 0.0,
        overhead_ms: float = 0.0,
        pinned: bool = False,
    ) -> None:
        """Arm the DX520/DX521 checks with per-stage predicted ms
        (``ConformanceModel.latency_predictions`` output, or an
        explicit conf pin — a pin wins over computed predictions and
        is never overwritten by the host's calibration)."""
        if self.latency_pinned and not pinned:
            return
        self.latency = {
            k: float(v) for k, v in (predictions or {}).items() if v
        }
        self._latency_compute_ms = float(compute_ms)
        self._latency_overhead_ms = float(overhead_ms)
        self.latency_pinned = self.latency_pinned or pinned

    # -- transitions -----------------------------------------------------
    def _transition(
        self, key: str, in_drift: bool, make_event,
    ) -> Optional[DriftEvent]:
        if in_drift and key not in self._active:
            self._active.add(key)
            self.drift_count += 1
            return make_event()
        if not in_drift:
            self._active.discard(key)
        return None

    # -- the per-batch pass ----------------------------------------------
    def observe(
        self, metrics: Dict[str, float],
        batch_time_ms: Optional[int] = None,
    ) -> tuple:
        """Feed one finished batch's metrics. Returns
        ``(gauges, events)``: gauges are ``Conformance_*`` entries for
        the batch's metric dict; events are the drift transitions that
        fired (typed, flight-recorder-bound)."""
        self.batches += 1
        gauges: Dict[str, float] = {}
        events: List[DriftEvent] = []
        warmed = self.batches > self.warmup

        # DX501: observed D2H bytes vs the modeled per-batch transfer
        d2h = metrics.get("Transfer_D2HBytes")
        predicted_d2h = self.model.d2h_bytes_per_batch
        if d2h is not None and predicted_d2h:
            self._d2h.append(float(d2h))
            mean = sum(self._d2h) / len(self._d2h)
            ratio = mean / float(predicted_d2h)
            gauges["Conformance_D2HBytes_Ratio"] = ratio
            ev = self._transition(
                "DX501", warmed and ratio > self.d2h_ratio_high,
                lambda: DriftEvent(
                    "DX501", "Transfer_D2HBytes", mean,
                    float(predicted_d2h), ratio, batch_time_ms,
                    f"windowed D2H bytes {mean:.0f} exceed modeled "
                    f"{float(predicted_d2h):.0f}/batch by "
                    f"{ratio:.2f}x (> {self.d2h_ratio_high}x)",
                ),
            )
            if ev:
                events.append(ev)

        # DX502: per-output occupancy vs modeled cardinality
        for name, pred in self.model.outputs.items():
            rows_pred = pred.get("rows")
            if not rows_pred:
                continue
            observed = metrics.get(f"Output_{name}_Events_Count")
            if observed is None:
                continue
            win = self._occupancy.setdefault(
                name, deque(maxlen=self.window)
            )
            win.append(float(observed))
            mean = sum(win) / len(win)
            ratio = mean / float(rows_pred)
            gauges[f"Conformance_Occupancy_{name}_Ratio"] = ratio
            ev = self._transition(
                f"DX502:{name}",
                warmed and ratio > self.occupancy_factor,
                lambda n=name, m=mean, rp=float(rows_pred), r=ratio: DriftEvent(
                    "DX502", f"Output_{n}_Events_Count", m, rp, r,
                    batch_time_ms,
                    f"output '{n}' occupancy {m:.0f} rows/batch vs "
                    f"modeled cardinality {rp:.0f} "
                    f"({r:.2f}x > {self.occupancy_factor}x) — re-check "
                    "declared key cardinality (DX200/DX202 inputs)",
                ),
            )
            if ev:
                events.append(ev)

        # DX510: observed mesh collective bytes vs the sharding model's
        # wire prediction (the DX7xx runtime counterpart)
        ici = metrics.get("Mesh_ICI_Bytes")
        predicted_ici = self.model.ici_wire_bytes_per_batch
        if ici is not None and predicted_ici:
            self._ici.append(float(ici))
            mean = sum(self._ici) / len(self._ici)
            ratio = mean / float(predicted_ici)
            gauges["Conformance_MeshIci_Ratio"] = ratio
            ev = self._transition(
                "DX510", warmed and ratio > self.ici_ratio_high,
                lambda: DriftEvent(
                    "DX510", "Mesh_ICI_Bytes", mean,
                    float(predicted_ici), ratio, batch_time_ms,
                    f"windowed mesh collective bytes {mean:.0f} exceed "
                    f"the sharding model's {float(predicted_ici):.0f}"
                    f"/batch by {ratio:.2f}x (> {self.ici_ratio_high}x) "
                    f"— the DX7xx partition plan missed traffic "
                    f"(re-validate with --mesh)",
                ),
            )
            if ev:
                events.append(ev)

        # DX511: the executed mesh program's collective-op census vs
        # its own post-warmup baseline (a change = a re-trace
        # repartitioned the step — the plan no longer describes it)
        n_coll = metrics.get("Mesh_Reshard_Count")
        if n_coll is not None:
            if warmed and self._collective_baseline is None:
                self._collective_baseline = float(n_coll)
            base = self._collective_baseline
            drifted = base is not None and float(n_coll) != base
            ev = self._transition(
                "DX511", drifted,
                lambda: DriftEvent(
                    "DX511", "Mesh_Reshard_Count", float(n_coll),
                    base or 0.0,
                    (float(n_coll) / base) if base else 0.0,
                    batch_time_ms,
                    f"mesh collective-op count changed "
                    f"{base:.0f} -> {n_coll:.0f} after warmup — the "
                    f"step re-traced into a different partition "
                    f"(dictionary growth or UDF refresh under the "
                    f"mesh; see DX204/DX600)",
                ),
            )
            if ev:
                events.append(ev)

        # DX520/DX521: observed per-stage latency p50 vs the roofline
        # prediction (the calibrated time model). The host merges the
        # windowed histogram percentiles into the metric dict BEFORE
        # this observe, so the comparison input is the same
        # Latency-<Stage>-p50 series every dashboard reads. DX521
        # replaces DX520 for a stage whose predicted time is all fixed
        # dispatch overhead (bytes*BW + flops/F tiny): going slow there
        # is dispatch-overhead domination, and more bandwidth won't fix
        # it — fewer/fused dispatches will.
        for stage, predicted_ms in self.latency.items():
            camel = MetricName.stage_metric(stage)[len("Latency-"):]
            observed_ms = metrics.get(f"Latency-{camel}-p50")
            if observed_ms is None or not predicted_ms:
                continue
            ratio = float(observed_ms) / float(predicted_ms)
            gauges[f"Conformance_StageTime_{camel}_Ratio"] = ratio
            # DX521 routing needs a known compute/overhead split (a
            # pinned prediction has none — drift there is plain DX520)
            overhead_bound = (
                stage == "device-step"
                and self._latency_overhead_ms > 0
                and self._latency_compute_ms <= self._latency_overhead_ms
            )
            code = "DX521" if overhead_bound else "DX520"
            # sub-floor predictions decline to judge (host-side fixed
            # costs dominate the observation); an explicit latency pin
            # is always judged
            judged = (
                self.latency_pinned
                or float(predicted_ms) >= self.stage_time_floor_ms
            )
            ev = self._transition(
                f"DX52x:{stage}",
                warmed and judged and ratio > self.stage_time_ratio_high,
                lambda s=stage, c=code, cm=camel, o=float(observed_ms),
                p=float(predicted_ms), r=ratio: DriftEvent(
                    c, f"Latency-{cm}-p50",
                    o, p, r, batch_time_ms,
                    (
                        f"stage '{s}' p50 {o:.2f}ms vs roofline "
                        f"{p:.2f}ms ({r:.1f}x > "
                        f"{self.stage_time_ratio_high}x)"
                        + (
                            " — the model is dispatch-overhead bound "
                            "(bytes/BW and flops/F are negligible): "
                            "the time is going into per-dispatch fixed "
                            "cost, not data movement; batch more work "
                            "per dispatch"
                            if c == "DX521" else
                            " — bandwidth regression, HBM re-layout or "
                            "an unmodeled slow path; re-profile with "
                            "POST /profile and re-validate with "
                            "--device"
                        )
                    ),
                ),
            )
            if ev:
                events.append(ev)

        # DX522: live HBM peak vs the DX2xx modeled footprint. The
        # observation is the per-window Hbm_PeakBytes sample
        # (jax memory_stats — absent on backends that don't report,
        # where the posture is silence like every missing input).
        hbm_peak = metrics.get("Hbm_PeakBytes")
        predicted_hbm = self.model.hbm_bytes
        if hbm_peak is not None and predicted_hbm:
            self._hbm.append(float(hbm_peak))
            mean = sum(self._hbm) / len(self._hbm)
            ratio = mean / float(predicted_hbm)
            gauges["Conformance_Hbm_Ratio"] = ratio
            ev = self._transition(
                "DX522", warmed and ratio > self.hbm_ratio_high,
                lambda m=mean, p=float(predicted_hbm), r=ratio: DriftEvent(
                    "DX522", "Hbm_PeakBytes", m, p, r, batch_time_ms,
                    f"live HBM peak {m:.0f}B drifted above the modeled "
                    f"footprint {p:.0f}B by {r:.2f}x "
                    f"(> {self.hbm_ratio_high}x) — fragmentation forcing "
                    f"re-layout, an unmodeled allocation, or stale "
                    f"capacity planning (re-run --device / --fleet)",
                ),
            )
            if ev:
                events.append(ev)

        # DX503: re-traces after warmup (steady state is trace-free)
        retraces = metrics.get("Retrace_Count")
        if retraces:
            ev = self._transition(
                "DX503", warmed,
                lambda: DriftEvent(
                    "DX503", "Retrace_Count", float(retraces), 0.0,
                    float(retraces), batch_time_ms,
                    f"{retraces:.0f} jit re-trace(s) after warmup — "
                    "the cost model assumes a trace-free steady state "
                    "(see DX204/DX3xx for static retrace hazards)",
                ),
            )
            if ev:
                events.append(ev)
        else:
            self._active.discard("DX503")

        if self.drift_count:
            gauges["Conformance_Drift_Count"] = float(self.drift_count)
        return gauges, events
