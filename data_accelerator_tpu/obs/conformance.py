"""Model-vs-observed conformance: runtime drift detection (DX5xx).

The static analysis tiers predict what a deployed flow will cost — the
DX2xx device-plan model is byte-exact against the XLA lowering
(``analysis/costmodel.py``), and the fleet placer admits jobs on those
numbers. Nothing until now checked the *running* job against them.
Config generation embeds the flow's machine-readable cost-model report
into the generated conf (``datax.job.process.conformance.model``, a
compact JSON produced by ``DevicePlanReport.runtime_model()``); at
runtime a ``ConformanceMonitor`` on each host compares windowed
observations — ``Transfer_D2HBytes``, per-output occupancy, retrace
counts — against those predictions and exports:

- ``Conformance_*`` gauges (observed/predicted ratios, merged into the
  per-batch metric dict so they ride the normal store/Prometheus/SPA
  path), and
- typed **drift events** into the flight recorder and metric store:

  | code | name | meaning |
  |---|---|---|
  | DX501 | d2h-bytes-drift | windowed observed D2H bytes exceed the modeled per-batch transfer by more than the tolerance band |
  | DX502 | occupancy-vs-modeled-cardinality | an output's observed row occupancy exceeds the modeled group/join cardinality — the capacity planning input was wrong |
  | DX503 | unmodeled-retrace | the jitted step re-traced after warmup; steady state is modeled as trace-free |

Events fire on the *transition* into drift (and re-arm on recovery), so
a sustained drift is one event, not one per batch; the cumulative
``Conformance_Drift_Count`` gauge keeps the total visible. This is the
observability substrate ROADMAP item 5's controller reads: you cannot
act on drift you cannot see.

Device-resident result path note: with background transfer
(``process.pipeline.backgroundtransfer``) ``observe()`` is called from
the host's landing thread, one call per batch finish in strict FIFO
order — the windowed series it judges (``Transfer_D2HBytes``, which
includes the counts vector's ``Sync_CountsBytes``, per-output
occupancy, retraces) are unchanged by the split, and the modeled
``d2hBytesPerBatch`` it compares against stays a wire-bytes term (the
donated output-slot HBM lives in the model's ``hbmBytes``, not here).
"""

from __future__ import annotations

import json
import logging
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

# runtime drift code registry (documented in OBSERVABILITY.md
# "Conformance monitoring (DX5xx)")
DRIFT_CODES: Dict[str, str] = {
    "DX501": "d2h-bytes-drift",
    "DX502": "occupancy-vs-modeled-cardinality",
    "DX503": "unmodeled-retrace",
}

# observed/predicted ratio above which DX501 fires (sized transfer makes
# observed < predicted the healthy direction; exceeding the model means
# the model missed traffic)
DEFAULT_D2H_RATIO_HIGH = 1.5
# observed rows / modeled cardinality above which DX502 fires
DEFAULT_OCCUPANCY_FACTOR = 2.0
# windowed samples required before ratios are judged (and before a
# retrace counts as unmodeled — the first trace IS the model)
DEFAULT_WARMUP_BATCHES = 4
DEFAULT_WINDOW = 16


@dataclass
class DriftEvent:
    """One typed model-vs-observed drift detection."""

    code: str
    metric: str
    observed: float
    predicted: float
    ratio: float
    batch_time_ms: Optional[int] = None
    message: str = ""

    def to_props(self) -> dict:
        return {
            "code": self.code,
            "name": DRIFT_CODES.get(self.code, self.code),
            "metric": self.metric,
            "observed": round(self.observed, 2),
            "predicted": round(self.predicted, 2),
            "ratio": round(self.ratio, 4),
            "batchTime": self.batch_time_ms,
            "message": self.message,
        }


@dataclass
class ConformanceModel:
    """The embedded slice of the DX2xx cost report a running host can
    check itself against. All fields optional — a missing prediction
    simply disables its checks (the missing-prediction posture is
    silence, not failure)."""

    d2h_bytes_per_batch: Optional[float] = None
    hbm_bytes: Optional[float] = None
    # output dataset -> {"rows": modeled cardinality, "capacity": padded}
    outputs: Dict[str, dict] = field(default_factory=dict)
    # per-stage d2hBytes (informational; the CLI/SPA render it)
    stages: List[dict] = field(default_factory=list)

    @classmethod
    def from_json(cls, text: str) -> Optional["ConformanceModel"]:
        try:
            obj = json.loads(text)
        except ValueError:
            logger.warning("unparseable conformance model; monitor off")
            return None
        if not isinstance(obj, dict):
            return None
        totals = obj.get("totals") or {}
        return cls(
            d2h_bytes_per_batch=totals.get("d2hBytesPerBatch"),
            hbm_bytes=totals.get("hbmBytes"),
            outputs={
                k: v for k, v in (obj.get("outputs") or {}).items()
                if isinstance(v, dict)
            },
            stages=list(obj.get("stages") or []),
        )

    @classmethod
    def from_conf(cls, dict_) -> Optional["ConformanceModel"]:
        raw = dict_.get_sub_dictionary(
            "datax.job.process.conformance."
        ).get("model")
        if not raw:
            return None
        return cls.from_json(raw)


class ConformanceMonitor:
    """Windowed model-vs-observed comparison, fed once per batch finish
    with the batch's metric dict (``FlowProcessor`` collect output plus
    the host's additions). Returns gauges to merge into the same dict
    and the drift events that fired this batch."""

    def __init__(
        self,
        model: ConformanceModel,
        flow: str = "",
        window: int = DEFAULT_WINDOW,
        warmup: int = DEFAULT_WARMUP_BATCHES,
        d2h_ratio_high: float = DEFAULT_D2H_RATIO_HIGH,
        occupancy_factor: float = DEFAULT_OCCUPANCY_FACTOR,
    ):
        self.model = model
        self.flow = flow
        self.window = max(1, int(window))
        self.warmup = max(1, int(warmup))
        self.d2h_ratio_high = float(d2h_ratio_high)
        self.occupancy_factor = float(occupancy_factor)
        self.batches = 0
        self.drift_count = 0
        self._d2h: deque = deque(maxlen=self.window)
        self._occupancy: Dict[str, deque] = {}
        # codes (keyed per metric) currently in drift — events fire on
        # the transition in, re-arm on recovery
        self._active: set = set()

    @classmethod
    def from_conf(cls, dict_, flow: str = "") -> Optional["ConformanceMonitor"]:
        model = ConformanceModel.from_conf(dict_)
        if model is None:
            return None
        sub = dict_.get_sub_dictionary("datax.job.process.conformance.")
        window = sub.get_int_option("window")
        warmup = sub.get_int_option("warmup")
        high = sub.get_double_option("d2hratiohigh")
        occ = sub.get_double_option("occupancyfactor")
        return cls(
            model,
            flow=flow,
            window=window if window is not None else DEFAULT_WINDOW,
            warmup=warmup if warmup is not None else DEFAULT_WARMUP_BATCHES,
            d2h_ratio_high=(
                high if high is not None else DEFAULT_D2H_RATIO_HIGH
            ),
            occupancy_factor=(
                occ if occ is not None else DEFAULT_OCCUPANCY_FACTOR
            ),
        )

    # -- transitions -----------------------------------------------------
    def _transition(
        self, key: str, in_drift: bool, make_event,
    ) -> Optional[DriftEvent]:
        if in_drift and key not in self._active:
            self._active.add(key)
            self.drift_count += 1
            return make_event()
        if not in_drift:
            self._active.discard(key)
        return None

    # -- the per-batch pass ----------------------------------------------
    def observe(
        self, metrics: Dict[str, float],
        batch_time_ms: Optional[int] = None,
    ) -> tuple:
        """Feed one finished batch's metrics. Returns
        ``(gauges, events)``: gauges are ``Conformance_*`` entries for
        the batch's metric dict; events are the drift transitions that
        fired (typed, flight-recorder-bound)."""
        self.batches += 1
        gauges: Dict[str, float] = {}
        events: List[DriftEvent] = []
        warmed = self.batches > self.warmup

        # DX501: observed D2H bytes vs the modeled per-batch transfer
        d2h = metrics.get("Transfer_D2HBytes")
        predicted_d2h = self.model.d2h_bytes_per_batch
        if d2h is not None and predicted_d2h:
            self._d2h.append(float(d2h))
            mean = sum(self._d2h) / len(self._d2h)
            ratio = mean / float(predicted_d2h)
            gauges["Conformance_D2HBytes_Ratio"] = ratio
            ev = self._transition(
                "DX501", warmed and ratio > self.d2h_ratio_high,
                lambda: DriftEvent(
                    "DX501", "Transfer_D2HBytes", mean,
                    float(predicted_d2h), ratio, batch_time_ms,
                    f"windowed D2H bytes {mean:.0f} exceed modeled "
                    f"{float(predicted_d2h):.0f}/batch by "
                    f"{ratio:.2f}x (> {self.d2h_ratio_high}x)",
                ),
            )
            if ev:
                events.append(ev)

        # DX502: per-output occupancy vs modeled cardinality
        for name, pred in self.model.outputs.items():
            rows_pred = pred.get("rows")
            if not rows_pred:
                continue
            observed = metrics.get(f"Output_{name}_Events_Count")
            if observed is None:
                continue
            win = self._occupancy.setdefault(
                name, deque(maxlen=self.window)
            )
            win.append(float(observed))
            mean = sum(win) / len(win)
            ratio = mean / float(rows_pred)
            gauges[f"Conformance_Occupancy_{name}_Ratio"] = ratio
            ev = self._transition(
                f"DX502:{name}",
                warmed and ratio > self.occupancy_factor,
                lambda n=name, m=mean, rp=float(rows_pred), r=ratio: DriftEvent(
                    "DX502", f"Output_{n}_Events_Count", m, rp, r,
                    batch_time_ms,
                    f"output '{n}' occupancy {m:.0f} rows/batch vs "
                    f"modeled cardinality {rp:.0f} "
                    f"({r:.2f}x > {self.occupancy_factor}x) — re-check "
                    "declared key cardinality (DX200/DX202 inputs)",
                ),
            )
            if ev:
                events.append(ev)

        # DX503: re-traces after warmup (steady state is trace-free)
        retraces = metrics.get("Retrace_Count")
        if retraces:
            ev = self._transition(
                "DX503", warmed,
                lambda: DriftEvent(
                    "DX503", "Retrace_Count", float(retraces), 0.0,
                    float(retraces), batch_time_ms,
                    f"{retraces:.0f} jit re-trace(s) after warmup — "
                    "the cost model assumes a trace-free steady state "
                    "(see DX204/DX3xx for static retrace hazards)",
                ),
            )
            if ev:
                events.append(ev)
        else:
            self._active.discard("DX503")

        if self.drift_count:
            gauges["Conformance_Drift_Count"] = float(self.drift_count)
        return gauges, events
