"""Machine-profile calibration: the time axis of the cost model.

The DX2xx/DX7xx closed forms predict *bytes and FLOPs*; turning those
into predicted *milliseconds* needs the machine constants of whatever
backend this process actually runs on — HBM stream bandwidth, dense
FLOP/s, the fixed per-dispatch overhead of one jitted call, D2H
transfer bandwidth and (under a mesh) per-link ICI bandwidth. This
module measures them once per process with tiny jit micro-probes
(~100 ms total on CPU, less on a real accelerator), so the roofline
latency model (``analysis/costmodel.py stage_time_ms``) and the DX52x
runtime conformance checks (``obs/conformance.py``) judge observations
against *this machine*, not a datasheet.

Probe design (each: warm once, take the best of a few reps — bandwidth
is a max, overhead a min, so best-of is the right estimator and is far
more run-to-run stable than a mean):

- **hbm read GB/s**: sum-reduce a large f32 array (reads N, writes ~0).
- **hbm write GB/s**: broadcast-fill the same shape (writes N, reads ~0).
- **flops GFLOP/s**: one square f32 matmul (2*n^3 FLOPs).
- **dispatch overhead µs**: a jitted scalar add, timed per blocking
  call — the fixed cost of getting ANY step onto the device and
  learning it finished (on a split-host tunnel this includes the RTT,
  which is exactly what a host-observed stage time contains too).
- **d2h GB/s**: ``jax.device_get`` of the probe array.
- **ici GB/s**: a psum across local devices (absent on 1-device hosts;
  the field is None and ICI latency terms fall back to the DX7xx wire
  model's bytes with no time prediction).

The profile persists as JSON — locally (``calibrationfile``) and,
like the persistent compile cache, through the shared object store
(``calibrationurl``, an ``objstore://`` URL) so a fleet of hosts on
identical hardware calibrates once. A cached profile is only reused
for the same backend + device kind. Every field exports as a
``Calib_*`` registry series so dashboards can see the machine model
their roofline ratios are judged against.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import asdict, dataclass
from typing import Dict, Optional

logger = logging.getLogger(__name__)

# probe sizing: big enough to stream past caches on an accelerator,
# small enough that the whole calibration stays ~100 ms on CPU
PROBE_ELEMS = 1 << 20  # 4 MiB of f32
PROBE_MATMUL_N = 256
# best-of over enough reps to shrug off scheduler noise on a loaded
# host (bandwidth probes are single-digit ms; reps are cheap)
PROBE_REPS = 8
DISPATCH_REPS = 10

# the version stamp persisted profiles carry; bump when probe semantics
# change so stale cached profiles recalibrate instead of mispredicting
# (v2 added the host JSON-decode probe — decode_rows_per_sec)
PROFILE_VERSION = 2

# decode probe sizing: enough rows that per-call overhead vanishes,
# small enough to stay ~10 ms
DECODE_PROBE_ROWS = 20_000


@dataclass
class MachineProfile:
    """Measured machine constants the latency closed forms consume."""

    backend: str
    device_kind: str
    hbm_read_gbps: float
    hbm_write_gbps: float
    flops_gflops: float
    dispatch_overhead_us: float
    d2h_gbps: float
    ici_gbps: Optional[float] = None
    # measured native ingest-decode rate over the reference payload
    # (rows/s; None when the native library is unavailable) — prices
    # the latency model's host-decode term so DX520 can judge
    # stage_decode_ms
    decode_rows_per_sec: Optional[float] = None
    probe_ms: float = 0.0
    version: int = PROFILE_VERSION

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, obj: dict) -> Optional["MachineProfile"]:
        try:
            known = {f for f in cls.__dataclass_fields__}  # noqa: SLF001
            return cls(**{k: v for k, v in obj.items() if k in known})
        except (TypeError, ValueError):
            return None

    def metrics(self) -> Dict[str, float]:
        """The ``Calib_*`` registry series (constants.MetricName)."""
        out = {
            "Calib_HbmReadGBps": self.hbm_read_gbps,
            "Calib_HbmWriteGBps": self.hbm_write_gbps,
            "Calib_FlopsGFlops": self.flops_gflops,
            "Calib_DispatchOverheadUs": self.dispatch_overhead_us,
            "Calib_D2HGBps": self.d2h_gbps,
        }
        if self.ici_gbps is not None:
            out["Calib_IciGBps"] = self.ici_gbps
        if self.decode_rows_per_sec is not None:
            out["Calib_DecodeRowsPerSec"] = self.decode_rows_per_sec
        return out


def _best_seconds(fn, reps: int = PROBE_REPS) -> float:
    """Min wall time of ``fn()`` over ``reps`` runs (after the caller
    warmed it): the least-interfered-with sample."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return max(best, 1e-9)


def _probe_decode_rate() -> Optional[float]:
    """Measure the native ingest decoder on a reference IoT-shaped
    payload (nested object, string + numeric + timestamp columns) —
    rows/s on THIS host, the constant the latency model's decode term
    is priced with. None when the native library is unavailable (the
    decode prediction then stays silent, like a missing ICI link)."""
    try:
        import json

        from ..core.schema import Schema, StringDictionary
        from ..native import NativeDecoder, native_available

        if not native_available():
            return None
        schema = Schema.from_spark_json(json.dumps({
            "type": "struct",
            "fields": [
                {"name": "d", "type": {"type": "struct", "fields": [
                    {"name": "id", "type": "long", "nullable": False,
                     "metadata": {}},
                    {"name": "kind", "type": "string", "nullable": False,
                     "metadata": {}},
                    {"name": "value", "type": "double", "nullable": False,
                     "metadata": {}},
                ]}, "nullable": False, "metadata": {}},
                {"name": "ts", "type": "timestamp", "nullable": True,
                 "metadata": {}},
            ],
        }))
        n = DECODE_PROBE_ROWS
        payload = ("\n".join(
            '{"d":{"id":%d,"kind":"K%d","value":%d.%03d},"ts":%d}'
            % (i % 97, i % 7, i % 100, i % 1000, 1_700_000_000_000 + i)
            for i in range(n)
        ) + "\n").encode()
        dec = NativeDecoder(schema, StringDictionary())
        dec.decode(payload, n)  # warm (build/trie/dict)
        best = _best_seconds(lambda: dec.decode(payload, n), reps=3)
        return round(n / best, 1)
    except Exception as e:  # noqa: BLE001 — the decode term is optional
        logger.debug("decode-rate probe unavailable: %s", e)
        return None


def calibrate(device=None) -> MachineProfile:
    """Run the micro-probes against ``device`` (default: the first
    local device) and return a fresh profile."""
    import jax
    import jax.numpy as jnp

    t_start = time.perf_counter()
    devices = jax.local_devices()
    dev = device if device is not None else devices[0]
    backend = jax.default_backend()
    kind = getattr(dev, "device_kind", backend) or backend

    x = jax.device_put(
        jnp.linspace(0.0, 1.0, PROBE_ELEMS, dtype=jnp.float32), dev
    )
    nbytes = PROBE_ELEMS * 4

    # inputs are committed to `dev` by device_put, so each jitted probe
    # runs there without the deprecated jit(device=...) pin
    read_fn = jax.jit(lambda a: jnp.sum(a))
    write_fn = jax.jit(lambda s: jnp.full((PROBE_ELEMS,), s, jnp.float32))
    m = jax.device_put(
        jnp.ones((PROBE_MATMUL_N, PROBE_MATMUL_N), jnp.float32), dev
    )
    mm_fn = jax.jit(lambda a: a @ a)
    tiny = jax.device_put(jnp.float32(1.0), dev)
    tick_fn = jax.jit(lambda a: a + 1.0)

    # warm every probe (trace + compile happen here, not in the timing)
    read_fn(x).block_until_ready()
    write_fn(tiny).block_until_ready()
    mm_fn(m).block_until_ready()
    tick_fn(tiny).block_until_ready()
    jax.device_get(x)

    read_s = _best_seconds(lambda: read_fn(x).block_until_ready())
    write_s = _best_seconds(lambda: write_fn(tiny).block_until_ready())
    mm_s = _best_seconds(lambda: mm_fn(m).block_until_ready())
    d2h_s = _best_seconds(lambda: jax.device_get(x))

    def ticks():
        for _ in range(DISPATCH_REPS):
            tick_fn(tiny).block_until_ready()

    tick_s = _best_seconds(ticks) / DISPATCH_REPS

    ici_gbps: Optional[float] = None
    if len(devices) > 1:
        try:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec

            mesh = Mesh(devices, ("d",))
            sharded = jax.device_put(
                jnp.ones((len(devices), PROBE_ELEMS // 8), jnp.float32),
                NamedSharding(mesh, PartitionSpec("d")),
            )
            psum_fn = jax.jit(
                lambda a: jnp.broadcast_to(jnp.sum(a, axis=0), a.shape)
            )
            psum_fn(sharded).block_until_ready()
            psum_s = _best_seconds(
                lambda: psum_fn(sharded).block_until_ready()
            )
            # ring all-reduce wire bytes of the [cols]-sized result
            from ..analysis.costmodel import allreduce_wire_bytes

            wire = allreduce_wire_bytes(
                (PROBE_ELEMS // 8) * 4, len(devices)
            )
            ici_gbps = wire / psum_s / 1e9
        except Exception as e:  # noqa: BLE001 — ici term is optional
            logger.debug("ici probe unavailable: %s", e)

    # subtract the measured fixed dispatch cost from the bandwidth
    # probes so a tunnel RTT doesn't masquerade as low bandwidth
    def bw(nb: float, s: float) -> float:
        return nb / max(s - tick_s, 1e-9) / 1e9

    decode_rate = _probe_decode_rate()

    profile = MachineProfile(
        backend=backend,
        device_kind=str(kind),
        hbm_read_gbps=round(bw(nbytes, read_s), 3),
        hbm_write_gbps=round(bw(nbytes, write_s), 3),
        flops_gflops=round(
            2.0 * PROBE_MATMUL_N ** 3 / max(mm_s - tick_s, 1e-9) / 1e9, 3
        ),
        dispatch_overhead_us=round(tick_s * 1e6, 3),
        d2h_gbps=round(nbytes / d2h_s / 1e9, 3),
        ici_gbps=round(ici_gbps, 3) if ici_gbps else None,
        decode_rows_per_sec=decode_rate,
        probe_ms=round((time.perf_counter() - t_start) * 1000.0, 1),
    )
    logger.info("machine profile calibrated: %s", profile.to_dict())
    return profile


# a conservative static fallback for contexts that must not touch a
# device (the analyzers run under JAX_PLATFORMS=cpu with no probes):
# the latency model then reports with profileSource="default" so
# readers know the milliseconds are datasheet-shaped, not measured
DEFAULT_PROFILE = MachineProfile(
    backend="default",
    device_kind="v5e-datasheet",
    hbm_read_gbps=819.0,
    hbm_write_gbps=819.0,
    flops_gflops=197_000.0,  # bf16 dense peak; f32 runs lower
    dispatch_overhead_us=50.0,
    d2h_gbps=8.0,  # PCIe-ish host link
    ici_gbps=49.0,  # v5e per-link half-duplex
)


# -- persistence ------------------------------------------------------------
def _matches(profile: MachineProfile, backend: str, kind: str) -> bool:
    return (
        profile.version == PROFILE_VERSION
        and profile.backend == backend
        and profile.device_kind == kind
    )


def load_profile(path: str) -> Optional[MachineProfile]:
    try:
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
        return MachineProfile.from_dict(obj) if isinstance(obj, dict) else None
    except (OSError, ValueError):
        return None


def save_profile(profile: MachineProfile, path: str) -> None:
    parent = os.path.dirname(os.path.abspath(path))
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(profile.to_dict(), f, separators=(",", ":"))
    os.replace(tmp, path)


def _objstore_client(url: str):
    from ..compile.aotcache import _parse_objstore_url
    from ..serve.objectstore import ObjectStoreClient

    endpoint, bucket, prefix = _parse_objstore_url(url)
    token = os.environ.get("DATAX_OBJSTORE_TOKEN")
    return ObjectStoreClient(endpoint, bucket, token=token), prefix


def _share_key(prefix: str, backend: str, kind: str) -> str:
    safe_kind = "".join(
        c if c.isalnum() or c in "._-" else "_" for c in kind
    )
    key = f"machineprofile-{backend}-{safe_kind}.json"
    return f"{prefix}/{key}" if prefix else key


def pull_shared(url: str, backend: str, kind: str) -> Optional[MachineProfile]:
    """Fetch a peer's profile for this backend+device from the shared
    store; best-effort (a dead store just means we calibrate)."""
    try:
        client, prefix = _objstore_client(url)
        data = client.get(_share_key(prefix, backend, kind))
        if not data:
            return None
        obj = json.loads(data.decode("utf-8"))
        return MachineProfile.from_dict(obj) if isinstance(obj, dict) else None
    except Exception as e:  # noqa: BLE001 — shared layer is best-effort
        logger.warning("machine-profile pull failed: %s", e)
        return None


def push_shared(url: str, profile: MachineProfile) -> bool:
    """Publish this host's profile so identical peers skip calibration."""
    try:
        client, prefix = _objstore_client(url)
        client.put(
            _share_key(prefix, profile.backend, profile.device_kind),
            json.dumps(profile.to_dict(), separators=(",", ":")).encode(),
        )
        return True
    except Exception as e:  # noqa: BLE001 — best-effort
        logger.warning("machine-profile push failed: %s", e)
        return False


# -- the once-per-process entry point ---------------------------------------
_cache_lock = threading.Lock()
_cached: Optional[MachineProfile] = None


def get_profile(
    cache_file: Optional[str] = None,
    share_url: Optional[str] = None,
    force: bool = False,
) -> MachineProfile:
    """The profile for this process's backend: process-cached, then the
    local ``cache_file``, then the shared store, then live calibration
    (whose result is persisted back through both layers). ``force``
    skips every cache (the ``obs calibrate`` CLI's re-measure)."""
    global _cached
    import jax

    backend = jax.default_backend()
    kind = (
        getattr(jax.local_devices()[0], "device_kind", backend) or backend
    )
    with _cache_lock:
        if not force:
            if _cached is not None and _matches(_cached, backend, str(kind)):
                return _cached
            if cache_file:
                p = load_profile(cache_file)
                if p is not None and _matches(p, backend, str(kind)):
                    _cached = p
                    return p
            if share_url:
                p = pull_shared(share_url, backend, str(kind))
                if p is not None and _matches(p, backend, str(kind)):
                    _cached = p
                    if cache_file:
                        save_profile(p, cache_file)
                    return p
        profile = calibrate()
        _cached = profile
        if cache_file:
            try:
                save_profile(profile, cache_file)
            except OSError as e:
                logger.warning("machine-profile save failed: %s", e)
        if share_url:
            push_shared(share_url, profile)
        return profile
