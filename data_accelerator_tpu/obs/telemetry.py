"""Telemetry: structured lifecycle events + exceptions with context props.

reference: datax-host telemetry/AppInsightLogger.scala:18-108 — a
process-wide logger that stamps every event/exception with context
properties (app name, executor/driver id) and ships them to AppInsights;
the engine emits events like ``streaming/batch/begin|end`` around every
micro-batch (EventHubStreamingFactory.scala:88,115) and
``error/streaming/process`` on batch failure
(CommonProcessorFactory.scala:382-398). The ASP.NET services do the same
via DataX.Utilities.Telemetry.

TPU-native stand-in: writers are pluggable — process log, JSONL trace
file (greppable flight recorder), and HTTP POST (a collector endpoint
under k8s). The jax profiler hook covers the deep-trace role the
reference delegates to AppInsights' profiler.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
import traceback
import urllib.request
from typing import Any, Dict, List, Optional

logger = logging.getLogger("data_accelerator_tpu.telemetry")


class TelemetryWriter:
    def write(self, record: Dict[str, Any]) -> None:
        raise NotImplementedError


class LogWriter(TelemetryWriter):
    def write(self, record: Dict[str, Any]) -> None:
        logger.info("%s", json.dumps(record, default=str))


class JsonlWriter(TelemetryWriter):
    """Append-only JSONL trace file — the local flight recorder.

    Size-capped: when the file would exceed ``max_bytes`` it rotates
    through ``<path>.1 .. <path>.N`` (``keep`` segments, oldest
    dropped), so a long-running job keeps at most ~(keep+1)x the cap on
    disk while the trace CLI can still reconstruct up to ``keep`` caps
    of history from the rotated segments. With ``compress`` the rotated
    segments are gzipped (``<path>.N.gz``) — the active file always
    stays plain text so `tail -f`/grep keep working. Rotation is a
    whole-file rename: a record (and therefore a span line) is never
    split across segments, so an in-progress batch's spans survive any
    rotation — some may land in ``.1`` while later ones land in the
    fresh active file, and the trace reader stitches them back.
    """

    DEFAULT_MAX_BYTES = 64 * 1024 * 1024

    def __init__(
        self,
        path: str,
        max_bytes: int = DEFAULT_MAX_BYTES,
        keep: int = 1,
        compress: bool = False,
    ):
        self.path = path
        self.max_bytes = max_bytes
        self.keep = max(1, int(keep))
        self.compress = bool(compress)
        self._lock = threading.Lock()
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        try:
            self._size = os.path.getsize(path)
        except OSError:
            self._size = 0

    @property
    def rotated_path(self) -> str:
        return self.path + (".1.gz" if self.compress else ".1")

    def _segment(self, i: int) -> str:
        return f"{self.path}.{i}" + (".gz" if self.compress else "")

    def _rotate(self) -> None:
        try:
            # shift .N-1 -> .N (dropping the oldest), then the active
            # file becomes .1 — gzipped first when compress is on
            for i in range(self.keep, 1, -1):
                if os.path.exists(self._segment(i - 1)):
                    os.replace(self._segment(i - 1), self._segment(i))
            if self.compress:
                import gzip
                import shutil

                with open(self.path, "rb") as src, gzip.open(
                    self._segment(1), "wb"
                ) as dst:
                    shutil.copyfileobj(src, dst)
                os.remove(self.path)
            else:
                os.replace(self.path, self._segment(1))
        except OSError:
            pass  # rotation failure must not lose the record

    def write(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record, default=str) + "\n"
        data = line.encode("utf-8")
        with self._lock:
            if self.max_bytes and self._size + len(data) > self.max_bytes \
                    and self._size > 0:
                self._rotate()
                self._size = 0
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(line)
            self._size += len(data)


class HttpWriter(TelemetryWriter):
    """Fire-and-forget POST to a collector (telemetry never fails the job).

    One worker thread drains a bounded queue; records are dropped (not
    queued unboundedly) when the collector is slow or down.
    """

    def __init__(self, endpoint: str, timeout_s: float = 5.0, max_queue: int = 1000):
        self.endpoint = endpoint
        self.timeout_s = timeout_s
        self._queue: "queue.Queue[Dict[str, Any]]" = queue.Queue(maxsize=max_queue)
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()

    def _drain(self) -> None:
        while True:
            record = self._queue.get()
            try:
                req = urllib.request.Request(
                    self.endpoint,
                    data=json.dumps(record, default=str).encode(),
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=self.timeout_s).read()
            except Exception as e:  # noqa: BLE001
                logger.debug("telemetry post failed: %s", e)

    def write(self, record: Dict[str, Any]) -> None:
        try:
            self._queue.put_nowait(record)
        except queue.Full:
            logger.debug("telemetry queue full; dropping record")


class TelemetryLogger:
    """Event/exception tracker with sticky context properties.

    reference: AppInsightLogger.scala — trackEvent/trackException with
    per-process context (app name, node role) merged into every record.
    """

    def __init__(
        self,
        app_name: str = "",
        writers: Optional[List[TelemetryWriter]] = None,
        context: Optional[Dict[str, str]] = None,
    ):
        self.app_name = app_name
        self.writers: List[TelemetryWriter] = (
            writers if writers is not None else [LogWriter()]
        )
        self.context: Dict[str, str] = {"app": app_name, **(context or {})}

    def with_context(self, **props: str) -> "TelemetryLogger":
        """Derived logger with extra sticky props (e.g. executor id)."""
        t = TelemetryLogger(self.app_name, self.writers, {**self.context, **props})
        return t

    def _emit(self, record: Dict[str, Any]) -> None:
        record = {"ts": time.time(), **self.context, **record}
        for w in self.writers:
            try:
                w.write(record)
            except Exception as e:  # noqa: BLE001 — never fail the caller
                logger.debug("telemetry writer failed: %s", e)

    def track_event(
        self,
        name: str,
        properties: Optional[Dict[str, Any]] = None,
        measurements: Optional[Dict[str, float]] = None,
    ) -> None:
        """reference: AppInsightLogger.trackEvent — e.g.
        ``streaming/batch/begin`` with batch-time props."""
        self._emit({
            "type": "event",
            "name": name,
            "properties": properties or {},
            "measurements": measurements or {},
        })

    def track_exception(
        self, error: BaseException, properties: Optional[Dict[str, Any]] = None
    ) -> None:
        self._emit({
            "type": "exception",
            "error": f"{type(error).__name__}: {error}",
            "stack": "".join(
                traceback.format_exception(
                    type(error), error, error.__traceback__
                )
            ),
            "properties": properties or {},
        })

    def track_span(
        self,
        name: str,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str],
        start_ts: float,
        duration_ms: float,
        properties: Optional[Dict[str, Any]] = None,
    ) -> None:
        """One batch-stage span (obs/tracing.py) — written through the
        same fan-out as events, so the JSONL flight recorder is also the
        trace log the ``obs trace`` CLI reconstructs from."""
        self._emit({
            "type": "span",
            "name": name,
            "trace": trace_id,
            "span": span_id,
            "parent": parent_id,
            "startTs": start_ts,
            "durationMs": round(float(duration_ms), 4),
            "properties": properties or {},
        })

    def track_metric(self, name: str, value: float,
                     properties: Optional[Dict[str, Any]] = None) -> None:
        self._emit({
            "type": "metric", "name": name, "value": value,
            "properties": properties or {},
        })

    # -- batch lifecycle convenience (the engine's event vocabulary) ------
    def batch_begin(self, batch_time_ms: int) -> None:
        self.track_event(
            "streaming/batch/begin", {"batchTime": batch_time_ms}
        )

    def batch_end(self, batch_time_ms: int,
                  measurements: Optional[Dict[str, float]] = None) -> None:
        self.track_event(
            "streaming/batch/end", {"batchTime": batch_time_ms}, measurements
        )


def from_conf(dict_) -> TelemetryLogger:
    """Build from ``datax.job.process.telemetry.*`` conf: ``tracefile``
    (JSONL path) and ``httppost`` (collector endpoint) writers plus the
    process log, mirroring the reference's appinsights conf gate
    (AppHost init path). ``tracefile.keep`` (rotated-segment count,
    default 1) and ``tracefile.compress`` (gzip rotated segments,
    default false) tune the flight recorder's rotation."""
    sub = dict_.get_sub_dictionary("datax.job.process.telemetry.")
    writers: List[TelemetryWriter] = [LogWriter()]
    trace = sub.get("tracefile")
    if trace:
        max_bytes = sub.get_long_option("tracefilemaxbytes")
        keep = sub.get_int_option("tracefile.keep")
        writers.append(JsonlWriter(
            trace,
            max_bytes=(
                max_bytes if max_bytes is not None
                else JsonlWriter.DEFAULT_MAX_BYTES
            ),
            keep=keep if keep is not None else 1,
            compress=(
                (sub.get_or_else("tracefile.compress", "false") or "")
                .lower() == "true"
            ),
        ))
    endpoint = sub.get("httppost")
    if endpoint:
        writers.append(HttpWriter(endpoint))
    return TelemetryLogger(dict_.get_metric_app_name(), writers)
