"""Per-replica telemetry frame publisher: the push half of the fleet
telemetry plane (the pull/merge half is obs/fleetview.py).

Every replica host accumulates its per-batch observability surfaces —
metric-counter deltas, last gauge values, the per-stage
``LatencyHistogram`` states, firing alerts, health, watermark/offset
progress, and per-source-offset-range ingested/emitted event counts —
into a compact windowed **telemetry frame** and publishes it to the
shared object store, keyed flow x replica x window::

    <prefix>/fleet/<flow>/<replica>/<window:08d>.json

Monarch-style push-based collection: the control plane never scrapes N
replica ``/metrics`` endpoints; each replica ships its own windowed
delta and the ``FleetView`` merges frames into fleet-level series
(counters summed, fixed-bucket histograms merged exactly).

Posture is **fail-open**: telemetry must never take down a batch. A
failed publish is counted (``Fleet_FramePublishError_Count``) and the
window's accumulators are RETAINED — the next successful frame carries
the missed window's deltas too, so counter conservation (the DX54x
delivery audit's input) survives transient store outages. Contrast the
state snapshot mirror (runtime/statepartition.py), which fails CLOSED:
dropped state is data loss, dropped telemetry is a gap on a dashboard.

The host calls ``record_batch`` from ``_finish_tail`` — which under
background transfer runs on the landing thread — so everything here is
lock-guarded. ``flush(final=True)`` (from ``StreamingHost.stop``) ships
the tail window marked ``"final": true``: the fleet view reads that
marker as a clean drain, distinguishing a completed replica from one
that died mid-stream (the DX542 stale-replica signal). ``kill()`` is
the chaos hook that suppresses exactly that final frame — simulating a
replica lost without drain (serve/scenarios.py rescale drill).

Frame schema is documented in OBSERVABILITY.md "Fleet telemetry
plane"; FRAME_VERSION gates forward-compat decoding.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Dict, List, Optional

from ..constants import MetricName
from ..core.config import SettingNamespace
from .histogram import HISTOGRAMS, HistogramRegistry

logger = logging.getLogger(__name__)

FRAME_VERSION = 1

# metric-name suffixes treated as window-summable counters; everything
# else in the per-batch metric dict is a gauge (last value wins). The
# per-batch *_Count/*_Events_Count/*_Bytes values are already deltas
# ("events this batch"), so summing them over the window yields the
# windowed delta the fleet rollup sums again across replicas.
_COUNTER_SUFFIXES = (
    "_Count", "_Bytes", "_GroupsDropped", "_JoinRowsDropped",
)


def is_counter_metric(name: str) -> bool:
    return name.endswith(_COUNTER_SUFFIXES)


class TelemetryFramePublisher:
    """Accumulates one replica's per-batch telemetry into windowed
    frames and publishes them to the shared object store."""

    def __init__(
        self,
        url: str,
        flow: str,
        replica: str = "r1",
        replica_index: int = 1,
        replica_count: int = 1,
        window_s: float = 10.0,
        metric_logger=None,
        histograms: Optional[HistogramRegistry] = None,
        token: Optional[str] = None,
        client=None,
        now_fn=time.time,
    ):
        from ..compile.aotcache import _parse_objstore_url
        from ..serve.objectstore import ObjectStoreClient

        if client is None:
            endpoint, bucket, prefix = _parse_objstore_url(url)
            client = ObjectStoreClient(endpoint, bucket, token=token)
        else:
            prefix = getattr(client, "_fleet_prefix", "")
        self.url = url
        self.flow = flow
        self.replica = replica
        self.replica_index = int(replica_index)
        self.replica_count = int(replica_count)
        self.window_s = float(window_s)
        self.metric_logger = metric_logger
        self.histograms = histograms if histograms is not None else HISTOGRAMS
        self._client = client
        self._prefix = prefix
        self._now = now_fn
        self._lock = threading.Lock()
        self._window_id = 0
        self._window_start_ms: Optional[int] = None
        self._window_opened_at: Optional[float] = None
        # window accumulators (reset only on a SUCCESSFUL publish)
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._offsets: Dict[str, List] = {}   # "src:part" -> [lo, hi]
        self._ingested: Dict[str, float] = {}  # source -> events
        self._emitted: Dict[str, float] = {}   # output -> events
        self._batches = 0
        self._last_batch_time_ms: Optional[int] = None
        self._last_health: Optional[dict] = None
        self._last_alerts: List[dict] = []
        self._killed = False
        # lifetime self-metrics (exported through metric_logger and on
        # every frame)
        self.frames_published = 0
        self.publish_errors = 0
        self.last_frame_bytes = 0
        self.last_publish_ms = 0.0

    @classmethod
    def from_conf(cls, dict_, flow: str, metric_logger=None,
                  histograms=None) -> Optional["TelemetryFramePublisher"]:
        """Build from ``datax.job.process.fleet.*`` conf; None when no
        ``publishurl`` is conf'd (fleet telemetry off)."""
        fleet_conf = dict_.get_sub_dictionary(
            SettingNamespace.JobProcessPrefix + "fleet."
        )
        url = fleet_conf.get("publishurl")
        if not url:
            return None
        state_conf = dict_.get_sub_dictionary(
            SettingNamespace.JobProcessPrefix + "state."
        )
        replica_index = int(state_conf.get_or_else("replicaindex", "1"))
        replica_count = int(state_conf.get_or_else("replicacount", "1"))
        try:
            return cls(
                url,
                flow=flow,
                replica=fleet_conf.get_or_else(
                    "replica", f"r{replica_index}"
                ),
                replica_index=replica_index,
                replica_count=replica_count,
                window_s=float(
                    fleet_conf.get_or_else("windowseconds", "10")
                ),
                metric_logger=metric_logger,
                histograms=histograms,
            )
        except Exception:  # noqa: BLE001 — telemetry init never kills a host
            logger.exception(
                "fleet publisher init failed (publishurl=%s); "
                "fleet telemetry disabled for this host", url
            )
            return None

    # -- accumulation -----------------------------------------------------
    def record_batch(
        self,
        metrics: Dict[str, float],
        consumed: Optional[Dict] = None,
        batch_time_ms: Optional[int] = None,
        health: Optional[dict] = None,
        alerts: Optional[List[dict]] = None,
    ) -> None:
        """Fold one finished batch into the open window; publishes the
        frame when the window has elapsed (``window_s`` 0 publishes
        every batch). Thread-safe; never raises."""
        try:
            with self._lock:
                if self._killed:
                    return
                now = self._now()
                if self._window_opened_at is None:
                    self._window_opened_at = now
                    self._window_start_ms = batch_time_ms
                for name, value in metrics.items():
                    try:
                        v = float(value)
                    except (TypeError, ValueError):
                        continue
                    if is_counter_metric(name):
                        self._counters[name] = (
                            self._counters.get(name, 0.0) + v
                        )
                        if name.startswith("Input_") \
                                and name.endswith("_Events_Count"):
                            src = name[len("Input_"):-len("_Events_Count")]
                            self._ingested[src] = (
                                self._ingested.get(src, 0.0) + v
                            )
                        elif name.startswith("Output_") \
                                and name.endswith("_Events_Count"):
                            out = name[len("Output_"):-len("_Events_Count")]
                            self._emitted[out] = (
                                self._emitted.get(out, 0.0) + v
                            )
                    else:
                        self._gauges[name] = v
                for key, rng in (consumed or {}).items():
                    if isinstance(key, tuple):
                        key = ":".join(str(k) for k in key)
                    try:
                        lo, hi = rng
                    except (TypeError, ValueError):
                        continue
                    cur = self._offsets.get(str(key))
                    if cur is None:
                        self._offsets[str(key)] = [lo, hi]
                    else:
                        cur[0] = min(cur[0], lo)
                        cur[1] = max(cur[1], hi)
                self._batches += 1
                if batch_time_ms is not None:
                    self._last_batch_time_ms = batch_time_ms
                if health is not None:
                    self._last_health = health
                if alerts is not None:
                    self._last_alerts = list(alerts)
                due = now - self._window_opened_at >= self.window_s
            if due:
                self.flush()
        except Exception:  # noqa: BLE001 — fail-open: telemetry never
            logger.exception("fleet frame accumulation failed")  # kills a batch

    # -- publication ------------------------------------------------------
    def _build_frame(self, final: bool) -> dict:
        hists = {}
        for f, stage, h in self.histograms.items():
            if f == self.flow:
                hists[stage] = h.to_state()
        now_ms = int(self._now() * 1000)
        return {
            "version": FRAME_VERSION,
            "flow": self.flow,
            "replica": self.replica,
            "replicaIndex": self.replica_index,
            "replicaCount": self.replica_count,
            "window": self._window_id,
            "windowSeconds": self.window_s,
            "windowStartMs": self._window_start_ms,
            "publishedAtMs": now_ms,
            "final": final,
            "batches": self._batches,
            "counters": dict(self._counters),
            "gauges": dict(self._gauges),
            "histograms": hists,
            "alerts": list(self._last_alerts),
            "health": self._last_health,
            "watermark": {
                "batchTimeMs": self._last_batch_time_ms,
                "offsets": {k: list(v) for k, v in self._offsets.items()},
            },
            "delivery": {
                "ingested": dict(self._ingested),
                "emitted": dict(self._emitted),
            },
            "framesPublished": self.frames_published,
            "publishErrors": self.publish_errors,
        }

    def _frame_key(self) -> str:
        parts = [
            self._prefix, "fleet", self.flow, self.replica,
            f"{self._window_id:08d}.json",
        ]
        return "/".join(p for p in parts if p)

    def flush(self, final: bool = False) -> bool:
        """Publish the open window (even if empty when ``final`` — the
        drain marker must ship). Returns True on success; on failure
        the accumulators are retained for the next attempt."""
        with self._lock:
            if self._killed:
                return False
            if self._batches == 0 and not final:
                return True  # nothing to ship yet
            frame = self._build_frame(final)
            key = self._frame_key()
        body = json.dumps(frame, default=str).encode("utf-8")
        t0 = self._now()
        try:
            self._client.put(key, body)
        except Exception:  # noqa: BLE001 — fail-open by contract
            with self._lock:
                self.publish_errors += 1
            logger.warning(
                "fleet frame publish failed (%s); window retained "
                "(%d error(s) so far)", key, self.publish_errors,
                exc_info=True,
            )
            self._send_self_metric(
                MetricName.FLEET_FRAME_PUBLISH_ERROR,
                float(self.publish_errors),
            )
            return False
        publish_ms = (self._now() - t0) * 1000.0
        with self._lock:
            self.frames_published += 1
            self.last_frame_bytes = len(body)
            self.last_publish_ms = publish_ms
            self._window_id += 1
            self._window_opened_at = None
            self._window_start_ms = None
            self._counters.clear()
            self._gauges.clear()
            self._offsets.clear()
            self._ingested.clear()
            self._emitted.clear()
            self._batches = 0
            frames = self.frames_published
        self._send_self_metric(MetricName.FLEET_FRAMES, float(frames))
        self._send_self_metric(MetricName.FLEET_FRAME_BYTES, float(len(body)))
        self._send_self_metric(MetricName.FLEET_FRAME_PUBLISH_MS, publish_ms)
        return True

    def kill(self) -> None:
        """Chaos hook: stop publishing WITHOUT the final drain frame —
        the telemetry shape of a replica killed without drain. The
        fleet view must then mark this replica stale (DX542) once it
        goes quiet (serve/scenarios.py rescale drill)."""
        with self._lock:
            self._killed = True

    def _send_self_metric(self, metric: str, value: float) -> None:
        if self.metric_logger is None:
            return
        try:
            self.metric_logger.send_metric(
                metric, value, int(self._now() * 1000)
            )
        except Exception:  # noqa: BLE001 — self-metrics are best-effort
            logger.debug("fleet self-metric %s emit failed", metric)
