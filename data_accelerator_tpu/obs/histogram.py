"""Fixed-bucket latency histograms: one measurement path, three readers.

The per-stage latency decomposition (decode -> dispatch -> device step ->
completion sync -> collect) previously existed only offline in bench.py;
this type makes it a live, queryable distribution:

- **Prometheus exposition** reads the fixed cumulative buckets
  (``/metrics`` renders ``_bucket``/``_sum``/``_count`` series so any
  scraper can compute quantiles its own way).
- **Live percentiles** (p50/p95/p99 stat tiles, the
  ``Latency-<stage>-p99`` MetricStore series) read a bounded window of
  recent raw samples — exact over the window, not bucket-interpolated,
  so the numbers match what an offline ``np.percentile`` over the same
  samples would say.
- **bench.py** observes its sequential-latency stages into the same
  type, so BENCH_*.json and the live dashboard cannot drift: one
  ``observe()``, one ``percentile()``.

reference analog: AppInsights aggregates the ``streaming/batch/*``
timings server-side; here the aggregation is in-process and the
exposition is Prometheus text.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

# Default bucket bounds in milliseconds. Spans the whole regime the
# engine sees: sub-ms host stages, ~10-100 ms device/tunnel round trips,
# multi-second stragglers. Cumulative Prometheus semantics (le=bound).
DEFAULT_BUCKETS_MS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100,
    250, 500, 1000, 2500, 5000, 10000, 30000,
)

# raw-sample window for exact percentiles (a ring buffer; ~16 KiB per
# stage at 2048 float samples — bounded on a long-running job)
DEFAULT_WINDOW = 2048


class LatencyHistogram:
    """Thread-safe fixed-bucket histogram + recent-sample window."""

    def __init__(
        self,
        buckets_ms: Sequence[float] = DEFAULT_BUCKETS_MS,
        window: int = DEFAULT_WINDOW,
    ):
        self.buckets_ms: Tuple[float, ...] = tuple(buckets_ms)
        self._counts = [0] * (len(self.buckets_ms) + 1)  # +1 = +Inf
        self.count = 0
        self.sum_ms = 0.0
        self._window: List[float] = []
        # per-sample trace ids, parallel to _window: the exemplar side
        # channel (a p99 spike on a dashboard links straight to the
        # offending batch's trace — `obs trace <id>`)
        self._window_ids: List[Optional[str]] = []
        self._window_cap = window
        self._window_pos = 0
        self._lock = threading.Lock()

    def observe(self, ms: float, trace_id: Optional[str] = None) -> None:
        ms = float(ms)
        with self._lock:
            i = 0
            for i, b in enumerate(self.buckets_ms):
                if ms <= b:
                    break
            else:
                i = len(self.buckets_ms)
            self._counts[i] += 1
            self.count += 1
            self.sum_ms += ms
            if len(self._window) < self._window_cap:
                self._window.append(ms)
                self._window_ids.append(trace_id)
            else:
                self._window[self._window_pos] = ms
                self._window_ids[self._window_pos] = trace_id
                self._window_pos = (self._window_pos + 1) % self._window_cap

    def exemplar(self) -> Optional[Dict[str, object]]:
        """The max-duration observation currently in the window and its
        trace id: ``{"ms": float, "traceId": str|None}``. None when the
        window is empty. This is what ``/metrics`` attaches as the
        OpenMetrics-style exemplar on the +Inf bucket."""
        with self._lock:
            if not self._window:
                return None
            i = max(range(len(self._window)), key=self._window.__getitem__)
            return {"ms": self._window[i], "traceId": self._window_ids[i]}

    def percentile(self, q: float) -> Optional[float]:
        """Exact percentile over the recent-sample window (numpy's
        'linear' interpolation, so offline np.percentile over the same
        samples agrees bit-for-bit). None when empty."""
        with self._lock:
            data = sorted(self._window)
        n = len(data)
        if n == 0:
            return None
        if n == 1:
            return data[0]
        pos = (q / 100.0) * (n - 1)
        lo = int(math.floor(pos))
        hi = min(lo + 1, n - 1)
        frac = pos - lo
        return data[lo] * (1.0 - frac) + data[hi] * frac

    def snapshot(self) -> Dict[str, object]:
        """Cumulative bucket counts + count/sum, Prometheus-shaped."""
        with self._lock:
            counts = list(self._counts)
            total = self.count
            s = self.sum_ms
        cumulative = []
        acc = 0
        for c in counts:
            acc += c
            cumulative.append(acc)
        return {
            "buckets": list(self.buckets_ms),
            "cumulative": cumulative,  # last entry == count (the +Inf bucket)
            "count": total,
            "sum_ms": s,
        }

    def to_state(self) -> Dict[str, object]:
        """Full serializable state: per-bucket (non-cumulative) counts
        plus the raw sample window. The fleet telemetry frame carries
        this shape (obs/publisher.py) so a control-plane merge is exact
        — both the bucket counts AND the window percentiles survive the
        wire (``from_state`` -> ``merge`` round-trip)."""
        with self._lock:
            return {
                "buckets": list(self.buckets_ms),
                "counts": list(self._counts),
                "count": self.count,
                "sumMs": self.sum_ms,
                "window": list(self._window),
            }

    @classmethod
    def from_state(cls, state: Dict[str, object]) -> "LatencyHistogram":
        """Rebuild a histogram from ``to_state()`` output. The window
        cap grows to hold every carried sample, so deserialization
        never evicts."""
        buckets = tuple(float(b) for b in state["buckets"])
        window = [float(v) for v in state.get("window") or []]
        h = cls(buckets, window=max(DEFAULT_WINDOW, len(window)))
        counts = [int(c) for c in state["counts"]]
        if len(counts) != len(buckets) + 1:
            raise ValueError(
                f"bucket/count shape mismatch: {len(counts)} counts for "
                f"{len(buckets)} bounds"
            )
        h._counts = counts
        h.count = int(state["count"])
        h.sum_ms = float(state.get("sumMs", state.get("sum_ms", 0.0)))
        h._window = window
        h._window_ids = [None] * len(window)
        return h

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Exact merge of two fixed-bucket histograms: element-wise
        bucket-count addition plus a UNION of the raw sample windows,
        returned as a new histogram (neither input is mutated).

        Requires identical bucket bounds — cross-replica aggregation
        only makes sense over one shared geometry (every host uses
        DEFAULT_BUCKETS_MS unless conf'd otherwise). The merged window
        cap is the sum of both inputs' caps, so no sample is evicted:
        ``merged.percentile(q)`` equals a percentile computed over the
        concatenated observations, and the operation is associative and
        commutative (tested in tests/test_fleetview.py)."""
        if self.buckets_ms != other.buckets_ms:
            raise ValueError(
                f"cannot merge histograms with different buckets: "
                f"{self.buckets_ms} != {other.buckets_ms}"
            )
        # lock ordering by id() so concurrent a.merge(b) / b.merge(a)
        # cannot deadlock
        first, second = sorted((self, other), key=id)
        with first._lock, second._lock:
            merged = LatencyHistogram(
                self.buckets_ms,
                window=self._window_cap + other._window_cap,
            )
            merged._counts = [
                a + b for a, b in zip(self._counts, other._counts)
            ]
            merged.count = self.count + other.count
            merged.sum_ms = self.sum_ms + other.sum_ms
            merged._window = list(self._window) + list(other._window)
            merged._window_ids = (
                list(self._window_ids) + list(other._window_ids)
            )
        return merged


class HistogramRegistry:
    """(flow, stage) -> LatencyHistogram, lazily created.

    The process-wide ``HISTOGRAMS`` instance plays the role METRIC_STORE
    plays for gauges: the one-box aggregation point every exposition
    endpoint reads.
    """

    def __init__(self, buckets_ms: Sequence[float] = DEFAULT_BUCKETS_MS):
        self.buckets_ms = tuple(buckets_ms)
        self._hists: Dict[Tuple[str, str], LatencyHistogram] = {}
        self._lock = threading.Lock()

    def get(self, flow: str, stage: str) -> LatencyHistogram:
        key = (flow, stage)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = LatencyHistogram(self.buckets_ms)
            return h

    def put(self, flow: str, stage: str, hist: LatencyHistogram) -> None:
        """Install a pre-built histogram (the fleet view's merged
        cross-replica histograms land here, obs/fleetview.py)."""
        with self._lock:
            self._hists[(flow, stage)] = hist

    def observe(
        self, flow: str, stage: str, ms: float,
        trace_id: Optional[str] = None,
    ) -> None:
        self.get(flow, stage).observe(ms, trace_id=trace_id)

    def percentile(self, flow: str, stage: str, q: float) -> Optional[float]:
        key = (flow, stage)
        with self._lock:
            h = self._hists.get(key)
        return h.percentile(q) if h is not None else None

    def items(self) -> List[Tuple[str, str, LatencyHistogram]]:
        with self._lock:
            return [(f, s, h) for (f, s), h in self._hists.items()]

    def stages(self, flow: str) -> List[str]:
        with self._lock:
            return sorted(s for (f, s) in self._hists if f == flow)

    def clear(self) -> None:
        with self._lock:
            self._hists.clear()


# the one-box process-wide registry (exposition endpoints read this)
HISTOGRAMS = HistogramRegistry()
