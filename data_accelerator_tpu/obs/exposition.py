"""Prometheus/health surface: ``/metrics``, ``/healthz``, ``/readyz``.

reference: the reference leans on AppInsights' live-metrics dashboard
(SURVEY §1 "live metrics dashboard") and k8s-style probes on the ASP.NET
services; the TPU-native runtime exposes the same operational contract
directly:

- ``GET /metrics``  — Prometheus text format: per-stage latency
  histograms (``datax_stage_latency_ms``), engine gauges (latest value
  of every MetricStore key), and health gauges (checkpoint age,
  batches/failures totals).
- ``GET /healthz``  — liveness: the process is serving; payload carries
  last-batch status for humans. Always 200 while the server runs.
- ``GET /readyz``   — readiness: 200 only when the engine has processed
  a batch recently, the last batch succeeded, and the checkpoint is not
  stale; 503 with the failing reasons otherwise.

The same rendering functions back the website server's endpoints, so
the control plane and every runtime host speak one exposition dialect.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from .histogram import HISTOGRAMS, HistogramRegistry
from .store import METRIC_STORE, MetricStore

logger = logging.getLogger(__name__)


class HealthState:
    """Mutable health snapshot a host updates as it runs.

    The readiness contract (readyz) derives from it: batch recency,
    last-batch success, checkpoint age.
    """

    # default EWMA weight for the pipeline-stall gauge (recent batches
    # dominate but one outlier stall can't flip readiness on its own);
    # conf ``observability.stallewmams`` overrides it as a HALF-LIFE in
    # milliseconds of batch time — see ``stall_ewma_half_life_ms``
    STALL_EWMA_ALPHA = 0.3

    def __init__(
        self,
        flow: str = "",
        checkpoint_interval_s: Optional[float] = None,
        batch_interval_s: float = 1.0,
        stall_fail_ms: Optional[float] = None,
        stall_ewma_half_life_ms: Optional[float] = None,
    ):
        self.flow = flow
        self.checkpoint_interval_s = checkpoint_interval_s
        self.batch_interval_s = batch_interval_s
        # sustained-stall readiness threshold: the smoothed
        # Pipeline_Stall_Ms above this means the pipeline is saturated
        # or wedged, not merely overlapping (default: 10 batch
        # intervals, floored at 10 s so split-host tunnel RTTs and
        # normal overlap never trip it)
        self.stall_fail_ms = (
            stall_fail_ms if stall_fail_ms is not None
            else max(10_000.0, 10.0 * batch_interval_s * 1000.0)
        )
        # smoothing weight for record_stall: conf'd as a half-life in
        # ms of batch time (observability.stallewmams — after one
        # half-life of batches a level shift covers half the distance),
        # converted to the per-sample alpha here; absent, the legacy
        # STALL_EWMA_ALPHA applies. The pilot reads the RESULTING gauge
        # (pipeline_stall_ms), so whatever constant readiness judges,
        # the controller judges too.
        if stall_ewma_half_life_ms is not None and stall_ewma_half_life_ms > 0:
            self.stall_ewma_alpha = 1.0 - 0.5 ** (
                max(1e-3, batch_interval_s * 1000.0)
                / float(stall_ewma_half_life_ms)
            )
        else:
            self.stall_ewma_alpha = self.STALL_EWMA_ALPHA
        self.started_at = time.time()
        self.batches_processed = 0
        self.batches_failed = 0
        self.last_batch_time_ms: Optional[int] = None
        self.last_batch_at: Optional[float] = None
        self.last_batch_ok: Optional[bool] = None
        self.last_batch_latency_ms: Optional[float] = None
        self.last_error: Optional[str] = None
        self.last_checkpoint_at: Optional[float] = None
        self.source_watermark_ms: Optional[int] = None
        self.pipeline_stall_ms: Optional[float] = None  # EWMA
        self.firing_alerts: List[dict] = []
        self._lock = threading.Lock()

    # -- host-side updates -------------------------------------------------
    def record_batch(
        self, batch_time_ms: Optional[int], ok: bool,
        latency_ms: Optional[float] = None, error: Optional[str] = None,
    ) -> None:
        with self._lock:
            if ok:
                self.batches_processed += 1
            else:
                self.batches_failed += 1
                self.last_error = error
            if batch_time_ms is not None:
                self.last_batch_time_ms = batch_time_ms
            self.last_batch_at = time.time()
            self.last_batch_ok = ok
            if latency_ms is not None:
                self.last_batch_latency_ms = latency_ms

    def record_checkpoint(self) -> None:
        with self._lock:
            self.last_checkpoint_at = time.time()

    def record_watermark(self, watermark_ms: int) -> None:
        """Latest event-time high-water mark the engine has processed
        (source lag = wall clock - watermark)."""
        with self._lock:
            self.source_watermark_ms = watermark_ms

    def record_stall(self, stall_ms: float) -> None:
        """Feed one batch's ``Pipeline_Stall_Ms`` into the smoothed
        stall gauge the readiness probe (and the pilot) judge."""
        a = self.stall_ewma_alpha
        with self._lock:
            prev = self.pipeline_stall_ms
            self.pipeline_stall_ms = (
                float(stall_ms) if prev is None
                else a * float(stall_ms) + (1.0 - a) * prev
            )

    def record_alerts(self, firing: List[dict]) -> None:
        """Latest firing-alert set from the host's AlertEngine — probes
        report it so k8s (and humans curling /readyz) see degradation,
        not just liveness."""
        with self._lock:
            self.firing_alerts = list(firing)

    # -- probes ------------------------------------------------------------
    def health(self) -> Dict[str, object]:
        with self._lock:
            now = time.time()
            return {
                "status": "ok" if self.last_batch_ok in (None, True)
                else "degraded",
                "flow": self.flow,
                "uptimeSeconds": round(now - self.started_at, 3),
                "batchesProcessed": self.batches_processed,
                "batchesFailed": self.batches_failed,
                "lastBatchTimeMs": self.last_batch_time_ms,
                "lastBatchOk": self.last_batch_ok,
                "lastBatchLatencyMs": self.last_batch_latency_ms,
                "lastBatchAgeSeconds": (
                    None if self.last_batch_at is None
                    else round(now - self.last_batch_at, 3)
                ),
                "lastError": self.last_error,
                "checkpointAgeSeconds": self.checkpoint_age_s(now),
                "sourceLagMs": self.source_lag_ms(now),
                "pipelineStallMs": (
                    None if self.pipeline_stall_ms is None
                    else round(self.pipeline_stall_ms, 1)
                ),
                "firingAlerts": [
                    a.get("name") for a in self.firing_alerts
                ],
            }

    def checkpoint_age_s(self, now: Optional[float] = None) -> Optional[float]:
        if self.last_checkpoint_at is None:
            return None
        return round((now or time.time()) - self.last_checkpoint_at, 3)

    def source_lag_ms(self, now: Optional[float] = None) -> Optional[float]:
        if self.source_watermark_ms is None:
            return None
        return round((now or time.time()) * 1000.0 - self.source_watermark_ms, 1)

    def readiness(self) -> List[str]:
        """Empty list when ready; otherwise the failing reasons."""
        reasons: List[str] = []
        with self._lock:
            now = time.time()
            if self.batches_processed == 0:
                reasons.append("no batch processed yet")
            if self.last_batch_ok is False:
                reasons.append(f"last batch failed: {self.last_error}")
            if self.last_batch_at is not None:
                stale_after = max(10.0, 5.0 * self.batch_interval_s)
                age = now - self.last_batch_at
                if age > stale_after:
                    reasons.append(
                        f"no batch for {age:.1f}s (> {stale_after:.1f}s)"
                    )
            if (
                self.checkpoint_interval_s is not None
                and self.last_checkpoint_at is not None
            ):
                age = now - self.last_checkpoint_at
                if age > 3.0 * self.checkpoint_interval_s:
                    reasons.append(
                        f"checkpoint stale: {age:.1f}s "
                        f"(interval {self.checkpoint_interval_s:.0f}s)"
                    )
            if (
                self.pipeline_stall_ms is not None
                and self.pipeline_stall_ms > self.stall_fail_ms
            ):
                reasons.append(
                    f"sustained pipeline stall: "
                    f"{self.pipeline_stall_ms:.0f}ms smoothed "
                    f"(> {self.stall_fail_ms:.0f}ms)"
                )
        return reasons


# -- Prometheus text rendering ---------------------------------------------
def _esc(value: str) -> str:
    return (
        str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(
    histograms: Optional[HistogramRegistry] = None,
    store: Optional[MetricStore] = None,
    health: Optional[HealthState] = None,
    alerts=None,
) -> str:
    """All process observability as Prometheus text exposition v0.0.4.

    ``alerts``: an ``obs.alerts.AlertEngine`` — per-rule
    ``datax_alert_firing`` gauges plus the ``datax_alerts_firing``
    total, evaluated at scrape time so ``GET /alerts`` and this
    exposition can never disagree on the firing set."""
    histograms = histograms if histograms is not None else HISTOGRAMS
    out: List[str] = []

    items = histograms.items()
    if items:
        out.append(
            "# HELP datax_stage_latency_ms Per-stage micro-batch latency."
        )
        out.append("# TYPE datax_stage_latency_ms histogram")
        for flow, stage, hist in sorted(items, key=lambda t: (t[0], t[1])):
            snap = hist.snapshot()
            labels = f'flow="{_esc(flow)}",stage="{_esc(stage)}"'
            for bound, cum in zip(snap["buckets"], snap["cumulative"]):
                out.append(
                    f'datax_stage_latency_ms_bucket{{{labels},'
                    f'le="{_fmt(bound)}"}} {cum}'
                )
            # OpenMetrics-style exemplar on the +Inf bucket: the trace
            # id of the window's max-duration observation, so a p99
            # spike on a dashboard links to `obs trace <id>` directly
            ex = hist.exemplar()
            ex_s = (
                f' # {{trace_id="{_esc(ex["traceId"])}"}} '
                f'{_fmt(ex["ms"])}'
                if ex and ex.get("traceId") else ""
            )
            out.append(
                f'datax_stage_latency_ms_bucket{{{labels},le="+Inf"}} '
                f'{snap["count"]}{ex_s}'
            )
            out.append(
                f'datax_stage_latency_ms_sum{{{labels}}} '
                f'{_fmt(snap["sum_ms"])}'
            )
            out.append(
                f'datax_stage_latency_ms_count{{{labels}}} {snap["count"]}'
            )

    if store is not None:
        keys = store.keys()
        if keys:
            out.append(
                "# HELP datax_metric_last_value Latest engine metric point "
                "per DATAX-<flow>:<metric> key."
            )
            out.append("# TYPE datax_metric_last_value gauge")
            for key in sorted(keys):
                pts = store.points(key)
                if not pts:
                    continue
                last = pts[-1]
                val = last.get("val")
                if not isinstance(val, (int, float)) or isinstance(val, bool):
                    continue  # detail-event members are JSON rows, not gauges
                app, _, metric = key.partition(":")
                out.append(
                    f'datax_metric_last_value{{app="{_esc(app)}",'
                    f'metric="{_esc(metric)}"}} {_fmt(val)}'
                )

    if health is not None:
        h = health.health()
        labels = f'flow="{_esc(health.flow)}"'
        out.append("# TYPE datax_batches_processed_total counter")
        out.append(
            f'datax_batches_processed_total{{{labels}}} '
            f'{h["batchesProcessed"]}'
        )
        out.append("# TYPE datax_batches_failed_total counter")
        out.append(
            f'datax_batches_failed_total{{{labels}}} {h["batchesFailed"]}'
        )
        out.append("# TYPE datax_last_batch_ok gauge")
        out.append(
            f'datax_last_batch_ok{{{labels}}} '
            f'{1 if h["lastBatchOk"] in (True, None) else 0}'
        )
        if h["checkpointAgeSeconds"] is not None:
            out.append("# TYPE datax_checkpoint_age_seconds gauge")
            out.append(
                f'datax_checkpoint_age_seconds{{{labels}}} '
                f'{_fmt(h["checkpointAgeSeconds"])}'
            )
        if h["sourceLagMs"] is not None:
            out.append("# TYPE datax_source_lag_ms gauge")
            out.append(
                f'datax_source_lag_ms{{{labels}}} {_fmt(h["sourceLagMs"])}'
            )
        if h["pipelineStallMs"] is not None:
            out.append("# TYPE datax_pipeline_stall_ms gauge")
            out.append(
                f'datax_pipeline_stall_ms{{{labels}}} '
                f'{_fmt(h["pipelineStallMs"])}'
            )

    if alerts is not None:
        snap = alerts.snapshot()
        firing_names = {a["name"] for a in snap["firing"]}
        out.append(
            "# HELP datax_alert_firing 1 when the named alert rule is "
            "firing."
        )
        out.append("# TYPE datax_alert_firing gauge")
        for rule in snap["rules"]:
            out.append(
                f'datax_alert_firing{{flow="{_esc(snap["flow"])}",'
                f'rule="{_esc(rule["name"])}",'
                f'severity="{_esc(rule.get("severity") or "warn")}"}} '
                f'{1 if rule["name"] in firing_names else 0}'
            )
        out.append("# TYPE datax_alerts_firing gauge")
        out.append(
            f'datax_alerts_firing{{flow="{_esc(snap["flow"])}"}} '
            f'{len(firing_names)}'
        )
    return "\n".join(out) + "\n"


# -- the runtime host's observability server -------------------------------
class ObservabilityServer:
    """Tiny HTTP server exposing /metrics, /healthz, /readyz for one
    runtime host (the website server exposes the same paths for the
    control plane via web/server.py)."""

    def __init__(
        self,
        health: HealthState,
        histograms: Optional[HistogramRegistry] = None,
        store: Optional[MetricStore] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        alerts=None,
        profiler=None,
    ):
        self.health = health
        self.histograms = histograms if histograms is not None else HISTOGRAMS
        self.store = store if store is not None else METRIC_STORE
        self.alerts = alerts  # obs.alerts.AlertEngine | None
        self.profiler = profiler  # obs.profiler.ProfilerSurface | None
        obs = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                logger.debug("obs %s", fmt % args)

            def _send(self, status: int, body: bytes, ctype: str) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = render_prometheus(
                        obs.histograms, obs.store, obs.health,
                        alerts=obs.alerts,
                    ).encode()
                    self._send(
                        200, body,
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif path == "/alerts":
                    if obs.alerts is None:
                        payload = {"flow": obs.health.flow, "rules": [],
                                   "firing": []}
                    else:
                        payload = obs.alerts.snapshot()
                    self._send(
                        200, json.dumps(payload, default=str).encode(),
                        "application/json",
                    )
                elif path == "/healthz":
                    self._send(
                        200,
                        json.dumps(obs.health.health()).encode(),
                        "application/json",
                    )
                elif path == "/readyz":
                    reasons = obs.health.readiness()
                    status = 200 if not reasons else 503
                    payload = {
                        "ready": not reasons,
                        "reasons": reasons,
                        **obs.health.health(),
                    }
                    self._send(
                        status, json.dumps(payload).encode(),
                        "application/json",
                    )
                elif path == "/profile":
                    # capture state for pollers (POST starts one)
                    if obs.profiler is None:
                        self._send(
                            501,
                            b'{"error": "profiler surface disabled"}',
                            "application/json",
                        )
                        return
                    payload = {
                        "available": obs.profiler.available,
                        "active": obs.profiler.active(),
                        "captures": obs.profiler.captures_count,
                    }
                    self._send(
                        200, json.dumps(payload).encode(),
                        "application/json",
                    )
                else:
                    self._send(
                        404, b'{"error": "not found"}', "application/json"
                    )

            def do_POST(self):
                path, _, query = self.path.partition("?")
                if path != "/profile":
                    self._send(
                        404, b'{"error": "not found"}', "application/json"
                    )
                    return
                if obs.profiler is None or not obs.profiler.available:
                    self._send(
                        501,
                        json.dumps({
                            "error": "jax profiler unavailable "
                                     "(surface disabled or backend "
                                     "without profiler support)",
                        }).encode(),
                        "application/json",
                    )
                    return
                seconds = None
                for part in query.split("&"):
                    k, _, v = part.partition("=")
                    if k == "seconds":
                        try:
                            seconds = float(v)
                        except ValueError:
                            pass
                from .profiler import DEFAULT_SECONDS

                result = obs.profiler.start(
                    seconds if seconds is not None else DEFAULT_SECONDS
                )
                status = 200 if "error" not in result else 409
                self._send(
                    status, json.dumps(result).encode(), "application/json"
                )

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        logger.info("observability endpoints on :%d", self.port)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
