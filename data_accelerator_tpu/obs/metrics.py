"""MetricLogger: fan-out of named metric points to configured sinks.

reference: datax-host telemetry/MetricLogger.scala:14-100 — metrics named
``DATAX-<flow>:<metric>`` go to Redis sorted sets, an EventHub, and/or an
HTTP endpoint depending on ``process.metric.*`` conf. Here: the in-proc
MetricStore stands in for Redis (one-box), HTTP POST is kept
wire-compatible with the local-mode website endpoint
(MetricLogger.scala:65-69), and an eventhub sink is a stub hook.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from typing import Dict, Iterable, Optional

from ..core.config import SettingDictionary
from .store import METRIC_STORE, MetricStore

logger = logging.getLogger(__name__)


class MetricLogger:
    def __init__(
        self,
        metric_app_name: str,
        store: Optional[MetricStore] = None,
        http_endpoint: Optional[str] = None,
        eventhub_sender=None,
    ):
        self.app_name = metric_app_name  # "DATAX-<flow>"
        self.store = store if store is not None else METRIC_STORE
        self.http_endpoint = http_endpoint
        self.eventhub_sender = eventhub_sender

    @staticmethod
    def from_conf(dict_: SettingDictionary) -> "MetricLogger":
        """reference: MetricsHandler.scala:12-35 reads
        process.metric.{redis,eventhub,httppost}. An ``eventhub`` value
        of ``host:port`` ships points to a MetricsIngestor side-car over
        TCP (the metrics-EventHub path, MetricLogger.scala:60-63)."""
        sub = dict_.get_sub_dictionary("datax.job.process.metric.")
        eventhub_sender = None
        conn = sub.get("eventhub") or ""
        h, _, p = conn.rpartition(":")
        if p.isdigit():
            from .ingestor import MetricStreamSender

            eventhub_sender = MetricStreamSender(h or "127.0.0.1", int(p))
        # the redis-analog sink: unset or any connection-ish value keeps
        # the shared in-proc MetricStore (the one-box stand-in for the
        # reference's Redis — the dashboard reads it back); an explicit
        # disable word detaches the job from the dashboard feed, the
        # analog of a reference job deployed with no redis connection
        redis = (sub.get("redis") or "").strip().lower()
        store = MetricStore() if redis in (
            "false", "off", "none", "disabled", "0",
        ) else None
        return MetricLogger(
            metric_app_name=dict_.get_metric_app_name(),
            store=store,
            http_endpoint=sub.get("httppost"),
            eventhub_sender=eventhub_sender,
        )

    def key(self, metric: str) -> str:
        return f"{self.app_name}:{metric}"

    def send_metric(self, metric: str, value, uts_ms: Optional[int] = None) -> None:
        if uts_ms is None:
            uts_ms = int(time.time() * 1000)
        self.store.add_point(self.key(metric), uts_ms, value)
        if self.http_endpoint:
            self._post_async([{"app": self.app_name, "metric": metric,
                              "uts": uts_ms, "value": value}])
        if self.eventhub_sender is not None:
            self.eventhub_sender(self.key(metric), uts_ms, value)

    def send_batch_metrics(
        self, metrics: Dict[str, float], uts_ms: Optional[int] = None
    ) -> None:
        """reference: MetricLogger.scala sendBatchMetrics via
        CommonProcessorFactory.scala:344-379."""
        for name, value in metrics.items():
            self.send_metric(name, value, uts_ms)

    def send_metric_events(
        self, metric: str, events: Iterable[dict], uts_ms: Optional[int] = None
    ) -> None:
        """Detail events (alert tables routed TO Metrics): stored as JSON
        members so DirectTable widgets can render rows
        (reference: metric sink rows with EventTime/MetricName/Pivot1)."""
        if uts_ms is None:
            uts_ms = int(time.time() * 1000)
        for ev in events:
            self.store.zadd(self.key(metric), float(uts_ms), json.dumps(ev, default=str))

    def _post_async(self, payload) -> None:
        def post():
            try:
                req = urllib.request.Request(
                    self.http_endpoint,
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=5).read()
            except Exception as e:  # metrics must never fail the batch
                logger.warning("metric http post failed: %s", e)

        threading.Thread(target=post, daemon=True).start()
