"""Declarative alert rules + an SLO/burn-rate evaluation engine.

reference: the reference platform's operators watched AppInsights live
metrics and alerted by hand (SURVEY §1 "babysitting"); production
stream processors instead declare alert rules over the live metric
stream and let the runtime evaluate them (Prometheus alerting rules,
multiwindow burn-rate alerts from the SRE workbook — PAPERS.md). This
module is that engine for the TPU runtime: rules are plain dicts
(JSON-serializable, shipped inside the generated flow conf under
``datax.job.process.alerts.rules``), evaluation reads the SAME live
surfaces the dashboards read (MetricStore points, histogram
percentiles, HealthState batch counters), and the firing set is served
uniformly by ``GET /alerts``, the Prometheus exposition
(``datax_alert_firing``) and the ``Alerts_Firing`` store series.

Rule shapes (see ``RULE_SCHEMA`` / ``validate_rules``):

- **threshold rule** — aggregate a metric over a trailing window and
  compare::

      {"name": "batch-p99-latency-slo", "metric": "Latency-Batch-p99",
       "op": ">", "threshold": 5000, "windowSeconds": 120,
       "forSeconds": 30, "severity": "page"}

  ``metric`` is the ``DATAX-<flow>:<metric>`` series name (the part
  after the colon). ``Latency-<Stage>-pNN`` names short-circuit to the
  live histogram percentile when a registry is wired — the exact same
  number the stat tiles show.

- **burn-rate rule** — error-budget burn over the batch success SLO::

      {"name": "batch-error-burn", "slo": {"objective": 0.99},
       "burnRate": 2.0, "windowSeconds": 300, "severity": "page"}

  burn = (failed/total over the window) / (1 - objective); the rule
  fires when burn exceeds ``burnRate`` (a burn of 1.0 consumes the
  whole error budget exactly at the SLO window's pace).

A rule's lifecycle is ok -> pending (condition true, waiting out
``forSeconds``) -> firing -> ok; evaluation is idempotent and cheap
(one pass over window points), so hosts run it every batch finish and
on every ``/alerts`` request.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

OPS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}

AGGREGATES = ("avg", "max", "min", "sum", "last")
SEVERITIES = ("info", "warn", "page")

# the optional ``action`` a rule may request while firing — ONE
# vocabulary shared with the pilot's actuation kinds
# (pilot/controller.py ACTION_KINDS): a firing rule with an action is a
# standing vote the controller folds into its decision table, so alerts
# and autopilot can never drift apart on what "backpressure" means
from ..pilot.controller import ACTION_KINDS as ACTIONS  # noqa: E402

# the declarative rule contract (documented in OBSERVABILITY.md "Alert
# rules"); validate_rules() enforces it — the CI satellite asserts every
# default-generated rule passes
RULE_SCHEMA = {
    "name": (str, True),
    "description": (str, False),
    "severity": (str, False),        # info | warn | page
    "action": (str, False),          # pilot actuation vote (ACTIONS)
    "windowSeconds": ((int, float), False),
    "forSeconds": ((int, float), False),
    # threshold form
    "metric": (str, False),
    "op": (str, False),              # > >= < <=
    "threshold": ((int, float), False),
    "aggregate": (str, False),       # avg | max | min | sum | last
    # burn-rate form
    "slo": (dict, False),            # {"objective": 0.99}
    "burnRate": ((int, float), False),
}


def validate_rules(rules) -> List[str]:
    """Schema-check a rule list; returns human-readable errors (empty =
    valid). Never raises — the caller decides whether bad rules are
    fatal (CLI --validate) or skipped (runtime engine)."""
    errors: List[str] = []
    if not isinstance(rules, list):
        return [f"rules must be a list, got {type(rules).__name__}"]
    seen = set()
    for i, r in enumerate(rules):
        where = f"rule[{i}]"
        if not isinstance(r, dict):
            errors.append(f"{where}: must be an object")
            continue
        name = r.get("name")
        if not name or not isinstance(name, str):
            errors.append(f"{where}: 'name' (string) is required")
        else:
            where = f"rule[{i}] {name!r}"
            if name in seen:
                errors.append(f"{where}: duplicate rule name")
            seen.add(name)
        for key, (types, _req) in RULE_SCHEMA.items():
            if key in r and not isinstance(r[key], types):
                errors.append(f"{where}: '{key}' has wrong type")
        unknown = set(r) - set(RULE_SCHEMA)
        if unknown:
            errors.append(f"{where}: unknown keys {sorted(unknown)}")
        is_threshold = "metric" in r
        is_burn = "slo" in r
        if not is_threshold and not is_burn:
            errors.append(f"{where}: needs 'metric' (threshold rule) "
                          "or 'slo' (burn-rate rule)")
        if is_threshold and is_burn:
            errors.append(f"{where}: 'metric' and 'slo' are exclusive")
        if is_threshold:
            if r.get("op") not in OPS:
                errors.append(
                    f"{where}: 'op' must be one of {sorted(OPS)}"
                )
            if not isinstance(r.get("threshold"), (int, float)) \
                    or isinstance(r.get("threshold"), bool):
                errors.append(f"{where}: numeric 'threshold' required")
            if r.get("aggregate") is not None \
                    and r["aggregate"] not in AGGREGATES:
                errors.append(
                    f"{where}: 'aggregate' must be one of {AGGREGATES}"
                )
        if is_burn:
            slo = r.get("slo") or {}
            obj = slo.get("objective")
            if not isinstance(obj, (int, float)) or isinstance(obj, bool) \
                    or not (0.0 < float(obj) < 1.0):
                errors.append(
                    f"{where}: slo.objective must be in (0, 1)"
                )
            unknown_slo = set(slo) - {"objective"}
            if unknown_slo:
                errors.append(
                    f"{where}: unknown slo keys {sorted(unknown_slo)}"
                )
            if not isinstance(r.get("burnRate"), (int, float)) \
                    or isinstance(r.get("burnRate"), bool):
                errors.append(f"{where}: numeric 'burnRate' required")
        if r.get("severity") is not None \
                and r.get("severity") not in SEVERITIES:
            errors.append(
                f"{where}: 'severity' must be one of {SEVERITIES}"
            )
        if r.get("action") is not None and r.get("action") not in ACTIONS:
            errors.append(
                f"{where}: 'action' must be one of {ACTIONS}"
            )
    return errors


def default_rules(flow: Optional[str] = None) -> List[dict]:
    """The standing rule set every auto-generated metrics config ships
    (codegen ``_generate_metrics_config``) and every generated conf
    carries: the p99 batch-latency SLO, conformance-ratio bounds over
    the embedded cost model, pipeline stall, and the batch error-budget
    burn rate. All names resolve through ``constants.MetricName`` —
    tier-1 asserts it."""
    return [
        {
            "name": "batch-p99-latency-slo",
            "metric": "Latency-Batch-p99",
            "op": ">", "threshold": 5000.0,
            "windowSeconds": 120, "forSeconds": 20,
            "severity": "page",
            "description": "p99 whole-batch latency above the 5 s SLO",
        },
        {
            "name": "conformance-d2h-drift",
            "metric": "Conformance_D2HBytes_Ratio",
            "op": ">", "threshold": 1.5,
            "windowSeconds": 300, "forSeconds": 30,
            "severity": "warn",
            "description": "observed D2H bytes drifting above the "
                           "cost model's per-batch prediction",
        },
        {
            "name": "pipeline-stall",
            "metric": "Pipeline_Stall_Ms",
            "op": ">", "threshold": 2000.0, "aggregate": "avg",
            "windowSeconds": 120, "forSeconds": 20,
            "severity": "warn",
            "description": "dispatch loop persistently stalled on the "
                           "window's oldest batch",
        },
        {
            # pending landings sustained above the default pipeline
            # depth (process.pipeline.depth, default 2): the background
            # transfer thread can't keep up with the dispatch loop, so
            # backpressure is about to serialize the pipeline
            "name": "background-transfer-backlog",
            "metric": "Transfer_Background_Pending",
            "op": ">", "threshold": 2.0, "aggregate": "avg",
            "windowSeconds": 120, "forSeconds": 20,
            "severity": "warn",
            # while firing, this rule votes for source backpressure in
            # the pilot's decision table (one rule vocabulary)
            "action": "backpressure",
            "description": "background result landings queuing beyond "
                           "the pipeline depth — sinks or D2H transfers "
                           "are slower than the dispatch loop",
        },
        {
            # LiveQuery serving plane SLO: p99 end-to-end execute
            # latency (queue wait + coalesced dispatch, the
            # Latency-LQExec histogram lq/service.py feeds) over the
            # interactive threshold. While firing it votes for source
            # backpressure in the pilot's decision table — the serving
            # plane and the streaming path share one chip, so shedding
            # ingest load is the actuator that frees device time
            "name": "lq-latency-slo",
            "metric": "Latency-LQExec-p99",
            "op": ">", "threshold": 1000.0,
            "windowSeconds": 120, "forSeconds": 20,
            "severity": "page",
            "action": "backpressure",
            "description": "p99 LiveQuery execute latency above the "
                           "1 s interactive SLO",
        },
        {
            "name": "batch-error-burn",
            "slo": {"objective": 0.99}, "burnRate": 2.0,
            "windowSeconds": 300,
            "severity": "page",
            "description": "batch failures burning the 99% success "
                           "error budget at 2x the sustainable rate",
        },
    ]


class AlertEngine:
    """Evaluates a rule list against the live metric surfaces.

    ``store``/``histograms``/``health`` are the same objects the
    exposition endpoints read — the engine adds no new measurement
    path, only judgement. All state is per-rule (pending/firing
    timestamps), so the engine is cheap to re-evaluate and safe to
    evaluate from both the batch loop and HTTP handler threads."""

    def __init__(
        self,
        rules: List[dict],
        flow: str = "",
        store=None,
        histograms=None,
        health=None,
        app_name: Optional[str] = None,
        now_fn=time.time,
    ):
        errors = validate_rules(rules)
        if errors:
            # runtime posture: drop invalid rules loudly, keep the rest
            logger.warning("invalid alert rules skipped: %s", errors)
            valid_names = set()
            checked = []
            for r in rules:
                if isinstance(r, dict) and not validate_rules([r]):
                    if r["name"] not in valid_names:
                        valid_names.add(r["name"])
                        checked.append(r)
            rules = checked
        self.rules = list(rules)
        self.flow = flow
        self.store = store
        self.histograms = histograms
        self.health = health
        self.app_name = app_name or (f"DATAX-{flow}" if flow else "")
        self.now = now_fn
        # rule name -> {"pending_since", "firing_since", "value"}
        self._state: Dict[str, dict] = {
            r["name"]: {"pending_since": None, "firing_since": None,
                        "value": None}
            for r in self.rules
        }
        # (epoch s, processed, failed) ring for burn-rate windows
        self._health_samples: List[Tuple[float, int, int]] = []

    @classmethod
    def from_conf(cls, dict_, flow: str = "", store=None,
                  histograms=None, health=None) -> Optional["AlertEngine"]:
        """Build from ``datax.job.process.alerts.rules`` (a JSON array,
        written by config generation); None when the conf carries no
        rules."""
        raw = dict_.get_sub_dictionary(
            "datax.job.process.alerts."
        ).get("rules")
        if not raw:
            return None
        try:
            rules = json.loads(raw)
        except ValueError:
            logger.warning("unparseable alerts.rules conf; alerts off")
            return None
        return cls(rules, flow=flow, store=store, histograms=histograms,
                   health=health)

    # -- value sources ---------------------------------------------------
    def _percentile_value(self, metric: str) -> Optional[float]:
        """``Latency-<Stage>-pNN`` straight from the live histograms."""
        if self.histograms is None or not metric.startswith("Latency-"):
            return None
        stem, _, q = metric.rpartition("-p")
        if not q.isdigit():
            return None
        from ..constants import MetricName

        for stage in MetricName.STAGES:
            if MetricName.stage_metric(stage) == stem:
                return self.histograms.percentile(
                    self.flow, stage, float(q)
                )
        return None

    def _window_points(self, metric: str, window_s: float,
                       now: float) -> List[float]:
        if self.store is None:
            return []
        key = f"{self.app_name}:{metric}" if self.app_name else metric
        pts = self.store.points(
            key, (now - window_s) * 1000.0, now * 1000.0
        )
        return [
            float(p["val"]) for p in pts
            if isinstance(p.get("val"), (int, float))
            and not isinstance(p.get("val"), bool)
        ]

    def _metric_value(self, rule: dict, now: float) -> Optional[float]:
        metric = rule["metric"]
        window_s = float(rule.get("windowSeconds") or 60)
        agg = rule.get("aggregate") or "avg"
        vals = self._window_points(metric, window_s, now)
        if not vals:
            # live histogram fallback for percentile series (a host
            # evaluating before its first store flush, or a rule over a
            # pctl the host doesn't export)
            return self._percentile_value(metric)
        if agg == "avg":
            return sum(vals) / len(vals)
        if agg == "max":
            return max(vals)
        if agg == "min":
            return min(vals)
        if agg == "sum":
            return float(sum(vals))
        return vals[-1]  # last

    def _burn_value(self, rule: dict, now: float) -> Optional[float]:
        """Error-budget burn rate over the rule's window from the
        HealthState batch counters."""
        if self.health is None:
            return None
        window_s = float(rule.get("windowSeconds") or 300)
        processed = self.health.batches_processed
        failed = self.health.batches_failed
        self._health_samples.append((now, processed, failed))
        # bound the ring to the largest plausible window
        cutoff = now - max(window_s, 3600.0)
        while self._health_samples and self._health_samples[0][0] < cutoff:
            self._health_samples.pop(0)
        base = None
        for t, p, f in self._health_samples:
            if t >= now - window_s:
                base = (p, f)
                break
        if base is None:
            base = (0, 0)
        d_total = (processed - base[0]) + (failed - base[1])
        if d_total <= 0:
            return None  # no batches in the window: nothing to judge
        error_rate = (failed - base[1]) / d_total
        budget = 1.0 - float(rule["slo"]["objective"])
        return error_rate / budget if budget > 0 else None

    # -- evaluation ------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation pass; returns the firing set (see
        ``firing``)."""
        now = self.now() if now is None else now
        for rule in self.rules:
            st = self._state[rule["name"]]
            if "slo" in rule:
                value = self._burn_value(rule, now)
                violated = (
                    value is not None and value > float(rule["burnRate"])
                )
            else:
                value = self._metric_value(rule, now)
                violated = value is not None and OPS[rule["op"]](
                    value, float(rule["threshold"])
                )
            st["value"] = value
            if not violated:
                st["pending_since"] = None
                st["firing_since"] = None
                continue
            if st["pending_since"] is None:
                st["pending_since"] = now
            if st["firing_since"] is None and (
                now - st["pending_since"] >= float(rule.get("forSeconds") or 0)
            ):
                st["firing_since"] = now
        return self.firing()

    def firing(self) -> List[dict]:
        out = []
        for rule in self.rules:
            st = self._state[rule["name"]]
            if st["firing_since"] is None:
                continue
            out.append({
                "name": rule["name"],
                "severity": rule.get("severity") or "warn",
                "since": st["firing_since"],
                "value": st["value"],
                "threshold": (
                    rule.get("threshold") if "metric" in rule
                    else rule.get("burnRate")
                ),
                "metric": rule.get("metric") or "batch-error-burn-rate",
                "description": rule.get("description") or "",
                **(
                    {"action": rule["action"]} if rule.get("action") else {}
                ),
            })
        return out

    def snapshot(self, evaluate: bool = True) -> dict:
        """The ``GET /alerts`` payload: every rule with its state plus
        the firing subset."""
        if evaluate:
            self.evaluate()
        rules = []
        for rule in self.rules:
            st = self._state[rule["name"]]
            state = (
                "firing" if st["firing_since"] is not None
                else "pending" if st["pending_since"] is not None
                else "ok"
            )
            rules.append({
                **{k: rule.get(k) for k in (
                    "name", "metric", "op", "threshold", "aggregate",
                    "windowSeconds", "forSeconds", "severity",
                    "description", "slo", "burnRate", "action",
                ) if rule.get(k) is not None},
                "state": state,
                "value": st["value"],
                "since": st["firing_since"],
            })
        return {
            "flow": self.flow,
            "rules": rules,
            "firing": self.firing(),
        }
