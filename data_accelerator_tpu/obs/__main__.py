"""Observability CLI.

``python -m data_accelerator_tpu.obs trace <batch_id> [--file F] [--json]``
reconstructs one micro-batch's span tree from the JSONL flight recorder
(the ``tracefile`` writer of obs/telemetry.py). ``<batch_id>`` is the
batch time in epoch ms (what ``streaming/batch/begin`` logs as
``batchTime``) or a raw trace id. Under cross-process propagation
(``datax.job.process.telemetry.parenttrace``) the rendered tree spans
the control-plane request down to the batch spans it caused.

Rotated segments (``<file>.N`` / ``<file>.N.gz`` — JsonlWriter
keep/compress rotation) are read oldest-first when present, so a batch
that rotated out mid-trace still reconstructs completely.

``python -m data_accelerator_tpu.obs alerts [--url U] [--json]``
fetches a host's (or the website's) ``GET /alerts`` and renders the
rule table with firing state; ``alerts --validate rules.json``
schema-checks a rule file (obs/alerts.py RULE_SCHEMA) and exits
non-zero on errors.

``python -m data_accelerator_tpu.obs profile <url> [--seconds N]``
POSTs ``/profile?seconds=N`` on a live host's observability port —
the on-demand jax profiler surface (obs/profiler.py) — and prints the
capture path the host returned.

``python -m data_accelerator_tpu.obs spans [--aggregate] [--file F]``
reads the flight recorder's span records; with ``--aggregate`` it
renders the flame table — stage -> count / total ms / p50 / p99 —
the offline rollup of the same per-stage decomposition the live
histograms serve.

``python -m data_accelerator_tpu.obs fleet [--url U] [--flow F]
[--output O] [--json]`` queries the control plane's fleet telemetry
rollup (``GET /fleet/metrics`` / ``/fleet/flows/<flow>``,
obs/fleetview.py): merged counters and histograms, per-replica status,
replica lineage, and the DX54x delivery-conservation audit.

``obs trace ... --stitch`` additionally groups the rendered spans by
the ``replica`` tag each host stamps on its batch spans, following the
flow's replica lineage across a rescale/handoff as one continuous
cross-replica tree (segments ordered by first activity, handoff
connectors between them).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional


def _rotated_paths(path: str) -> List[str]:
    """Every on-disk segment of a rotated flight recorder, oldest
    first: ``<path>.N[.gz] .. <path>.1[.gz]`` then the active file
    (JsonlWriter keep/compress rotation)."""
    import glob as _glob

    rotated = []
    for p in _glob.glob(path + ".*"):
        suffix = p[len(path) + 1:]
        if suffix.endswith(".gz"):
            suffix = suffix[:-3]
        if suffix.isdigit():
            rotated.append((int(suffix), p))
    out = [p for _, p in sorted(rotated, reverse=True)]
    if os.path.exists(path):
        out.append(path)
    return out


def load_spans(path: str) -> List[dict]:
    import gzip

    spans: List[dict] = []
    for p in _rotated_paths(path):
        opener = gzip.open if p.endswith(".gz") else open
        with opener(p, "rt", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("type") == "span":
                    spans.append(rec)
    return spans


def find_traces(spans: List[dict], batch_id: str) -> List[str]:
    """Trace ids whose root span matches ``batch_id`` (batchTime or
    trace id). Batch roots carry ``batchTime``; under cross-process
    propagation they also carry a ``parent`` pointing into the
    control-plane trace, so the match keys on the property alone."""
    ids: List[str] = []
    for s in spans:
        if s.get("trace") == batch_id and s["trace"] not in ids:
            ids.append(s["trace"])
    for s in spans:
        bt = (s.get("properties") or {}).get("batchTime")
        if bt is not None and str(bt) == str(batch_id) \
                and s["trace"] not in ids:
            ids.append(s["trace"])
    return ids


def format_tree(spans: List[dict]) -> str:
    """Render one trace's spans as an indented tree ordered by start."""
    by_id: Dict[str, dict] = {s["span"]: s for s in spans}
    children: Dict[Optional[str], List[dict]] = {}
    for s in spans:
        parent = s.get("parent")
        if parent is not None and parent not in by_id:
            parent = None  # orphan (rotation cut its parent) -> top level
        children.setdefault(parent, []).append(s)
    for kids in children.values():
        kids.sort(key=lambda s: (s.get("startTs") or 0))

    lines: List[str] = []

    def emit(span: dict, prefix: str, is_last: bool, depth: int) -> None:
        props = span.get("properties") or {}
        extras = " ".join(
            f"{k}={v}" for k, v in sorted(props.items())
        )
        dur = span.get("durationMs")
        head = "" if depth == 0 else prefix + ("└─ " if is_last else "├─ ")
        lines.append(
            f"{head}{span.get('name')} "
            f"{dur:.2f} ms" + (f"  [{extras}]" if extras else "")
        )
        kids = children.get(span["span"], [])
        child_prefix = (
            "" if depth == 0 else prefix + ("   " if is_last else "│  ")
        )
        for i, k in enumerate(kids):
            emit(k, child_prefix, i == len(kids) - 1, depth + 1)

    roots = children.get(None, [])
    for i, r in enumerate(roots):
        emit(r, "", i == len(roots) - 1, 0)
    return "\n".join(lines)


def _replica_of_trace(tspans: List[dict]) -> Optional[str]:
    """The replica tag of a trace: hosts publishing to the fleet plane
    stamp ``replica=<name>`` on their batch spans (runtime/host.py), so
    any tagged span identifies the segment."""
    for s in tspans:
        rep = (s.get("properties") or {}).get("replica")
        if rep:
            return str(rep)
    return None


def stitch_lineage(spans: List[dict],
                   trace_ids: List[str]) -> List[tuple]:
    """Group traces into replica lineage segments, ordered by first
    activity — the succession order a rescale handoff produces.
    Returns ``(replica, [trace ids])`` pairs; untagged traces land in a
    single ``(none)`` segment."""
    by_trace: Dict[str, List[dict]] = {}
    for s in spans:
        if s.get("trace") in trace_ids:
            by_trace.setdefault(s["trace"], []).append(s)
    segments: Dict[str, List[str]] = {}
    first_ts: Dict[str, float] = {}
    for tid, tspans in by_trace.items():
        rep = _replica_of_trace(tspans) or "(none)"
        segments.setdefault(rep, []).append(tid)
        ts = min(float(s.get("startTs") or 0) for s in tspans)
        first_ts[rep] = min(first_ts.get(rep, ts), ts)
        for lst in segments.values():
            lst.sort(key=lambda t: min(
                float(s.get("startTs") or 0) for s in by_trace[t]
            ))
    return sorted(segments.items(), key=lambda kv: first_ts[kv[0]])


def cmd_trace(args) -> int:
    spans = load_spans(args.file)
    if not spans:
        print(f"no spans found in {args.file}", file=sys.stderr)
        return 2
    if getattr(args, "stitch", False):
        return _trace_stitched(spans, args)
    trace_ids = find_traces(spans, args.batch_id)
    if not trace_ids:
        roots = sorted(
            {
                str((s.get("properties") or {}).get("batchTime"))
                for s in spans
                if (s.get("properties") or {}).get("batchTime") is not None
            }
        )
        print(
            f"no trace for batch {args.batch_id!r}; known batch ids: "
            f"{', '.join(roots[-10:]) or '(none)'}",
            file=sys.stderr,
        )
        return 1
    for tid in trace_ids:
        tspans = [s for s in spans if s.get("trace") == tid]
        if args.json:
            print(json.dumps(tspans, indent=1, default=str))
            continue
        print(f"trace {tid} ({len(tspans)} span(s))")
        print(format_tree(tspans))
    return 0


def _trace_stitched(spans: List[dict], args) -> int:
    """One continuous cross-replica tree: every trace matching
    ``batch_id`` — or, when the id is ``all``, every replica-tagged
    trace in the recorder — grouped into lineage segments."""
    if args.batch_id == "all":
        trace_ids = []
        for s in spans:
            if (s.get("properties") or {}).get("replica") \
                    and s["trace"] not in trace_ids:
                trace_ids.append(s["trace"])
    else:
        trace_ids = find_traces(spans, args.batch_id)
    if not trace_ids:
        print(f"no trace for {args.batch_id!r} to stitch",
              file=sys.stderr)
        return 1
    segments = stitch_lineage(spans, trace_ids)
    if args.json:
        print(json.dumps(
            [{"replica": rep, "traces": tids} for rep, tids in segments],
            indent=1,
        ))
        return 0
    print(f"replica lineage — {len(segments)} segment(s), "
          f"{len(trace_ids)} trace(s)")
    for i, (rep, tids) in enumerate(segments):
        if i:
            print("└→ handoff")
        nspans = sum(1 for s in spans if s.get("trace") in tids)
        print(f"■ replica {rep} ({len(tids)} trace(s), {nspans} span(s))")
        for tid in tids:
            tspans = [s for s in spans if s.get("trace") == tid]
            print(f"  trace {tid}")
            for line in format_tree(tspans).splitlines():
                print(f"    {line}")
    return 0


def cmd_alerts(args) -> int:
    from .alerts import validate_rules

    if args.validate:
        try:
            with open(args.validate, encoding="utf-8") as f:
                rules = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read rules file: {e}", file=sys.stderr)
            return 2
        errors = validate_rules(rules)
        if errors:
            for e in errors:
                print(e, file=sys.stderr)
            return 2
        print(f"{len(rules)} rule(s) valid")
        return 0
    import urllib.request

    url = args.url.rstrip("/") + "/alerts"
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            payload = json.loads(r.read() or b"{}")
    except OSError as e:
        print(f"cannot reach {url}: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(payload, indent=1, default=str))
        return 0
    firing = {a["name"] for a in payload.get("firing") or []}
    rules = payload.get("rules") or []
    flow = payload.get("flow") or ""
    print(f"alerts for {flow or '(unnamed)'} — "
          f"{len(firing)} firing / {len(rules)} rule(s)")
    for r in rules:
        state = r.get("state") or ("firing" if r["name"] in firing else "ok")
        mark = "!" if state == "firing" else (
            "~" if state == "pending" else " "
        )
        val = r.get("value")
        val_s = f"{val:.4g}" if isinstance(val, (int, float)) else "-"
        thr = r.get("threshold", r.get("burnRate"))
        print(f" {mark} {r['name']:<28} {state:<8} "
              f"value={val_s} threshold={thr} "
              f"severity={r.get('severity') or 'warn'}")
    return 1 if firing else 0


def _pctl(sorted_vals: List[float], q: float) -> float:
    """numpy-'linear' percentile over pre-sorted values (matches
    obs/histogram.py LatencyHistogram.percentile)."""
    n = len(sorted_vals)
    if n == 1:
        return sorted_vals[0]
    pos = (q / 100.0) * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def cmd_spans(args) -> int:
    spans = load_spans(args.file)
    if not spans:
        print(f"no spans found in {args.file}", file=sys.stderr)
        return 2
    if not args.aggregate:
        for s in spans[-args.limit:]:
            print(
                f"{s.get('trace')} {s.get('name'):<20} "
                f"{s.get('durationMs', 0):>10.2f} ms"
            )
        return 0
    # flame table: stage -> count/total/p50/p99 (+ the exemplar-style
    # max trace id, so the worst observation is one `obs trace` away)
    groups: Dict[str, List[dict]] = {}
    for s in spans:
        groups.setdefault(s.get("name") or "?", []).append(s)
    if args.json:
        out = []
        for name, ss in groups.items():
            durs = sorted(float(s.get("durationMs") or 0.0) for s in ss)
            worst = max(ss, key=lambda s: float(s.get("durationMs") or 0.0))
            out.append({
                "stage": name,
                "count": len(durs),
                "totalMs": round(sum(durs), 2),
                "p50Ms": round(_pctl(durs, 50), 3),
                "p99Ms": round(_pctl(durs, 99), 3),
                "maxMs": round(durs[-1], 3),
                "maxTrace": worst.get("trace"),
            })
        out.sort(key=lambda r: -r["totalMs"])
        print(json.dumps(out, indent=1))
        return 0
    rows = []
    for name, ss in groups.items():
        durs = sorted(float(s.get("durationMs") or 0.0) for s in ss)
        worst = max(ss, key=lambda s: float(s.get("durationMs") or 0.0))
        rows.append((
            name, len(durs), sum(durs), _pctl(durs, 50), _pctl(durs, 99),
            durs[-1], worst.get("trace"),
        ))
    rows.sort(key=lambda r: -r[2])
    print(f"{'stage':<24} {'count':>7} {'total ms':>12} "
          f"{'p50 ms':>10} {'p99 ms':>10} {'max ms':>10}  max trace")
    for name, n, total, p50, p99, mx, trace in rows:
        print(f"{name:<24} {n:>7} {total:>12.1f} "
              f"{p50:>10.2f} {p99:>10.2f} {mx:>10.2f}  {trace}")
    return 0


def cmd_profile(args) -> int:
    import urllib.parse
    import urllib.request

    url = (
        args.url.rstrip("/")
        + "/profile?"
        + urllib.parse.urlencode({"seconds": args.seconds})
    )
    try:
        req = urllib.request.Request(url, data=b"", method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            payload = json.loads(r.read() or b"{}")
            status = r.status
    except OSError as e:
        body = getattr(e, "read", lambda: b"")()
        try:
            payload = json.loads(body or b"{}")
        except ValueError:
            payload = {}
        if not payload:
            print(f"cannot reach {url}: {e}", file=sys.stderr)
            return 2
        status = getattr(e, "code", 500)
    if args.json:
        print(json.dumps(payload, indent=1))
        return 0 if status == 200 else 1
    if "error" in payload:
        print(f"profiler error: {payload['error']}", file=sys.stderr)
        return 1
    print(
        f"capture armed for {payload.get('seconds')}s -> "
        f"{payload.get('path')}"
    )
    print("open with: tensorboard --logdir <path>  (or xprof)")
    return 0


def cmd_fleet(args) -> int:
    import urllib.parse
    import urllib.request

    base = args.url.rstrip("/")
    if args.flow:
        url = f"{base}/fleet/flows/{urllib.parse.quote(args.flow)}"
        if args.output:
            url += "?" + urllib.parse.urlencode({"output": args.output})
    else:
        url = f"{base}/fleet/metrics"
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            payload = json.loads(r.read() or b"{}")
    except OSError as e:
        print(f"cannot reach {url}: {e}", file=sys.stderr)
        return 2
    payload = payload.get("result", payload)
    if args.json:
        print(json.dumps(payload, indent=1, default=str))
        return 0
    if not args.flow:
        flows = payload.get("flows") or {}
        print(f"fleet — {len(flows)} flow(s), "
              f"decode errors {payload.get('decodeErrors', 0)}, "
              f"last merge {payload.get('mergeMs', 0)} ms")
        for name in sorted(flows):
            f = flows[name]
            reps = f.get("replicas") or {}
            statuses = [r.get("status") for r in reps.values()]
            counts = (f.get("audit") or {}).get("counts") or {}
            bad = " ".join(
                f"{c}x{n}" for c, n in sorted(counts.items()) if n
            )
            print(f"  {name:<24} replicas={len(reps)} "
                  f"live={statuses.count('live')} "
                  f"stale={statuses.count('stale')} "
                  f"completed={statuses.count('completed')} "
                  f"alerts={len(f.get('alerts') or [])} "
                  f"audit={bad or 'conserved'}")
        return 0
    print(f"fleet flow {payload.get('flow')}")
    reps = payload.get("replicas") or {}
    for name in sorted(reps):
        r = reps[name]
        print(f"  {name:<20} {r.get('status'):<10} "
              f"frames={r.get('frames', 0)} batches={r.get('batches', 0)} "
              f"windows={r.get('windows')}")
    hists = payload.get("histograms") or {}
    for stage in sorted(hists):
        hh = hists[stage]
        print(f"  {stage:<20} n={hh.get('count')} p50={hh.get('p50')}ms "
              f"p95={hh.get('p95')}ms p99={hh.get('p99')}ms")
    lineage = payload.get("lineage") or []
    if lineage:
        print("  lineage: " + " -> ".join(
            str(seg.get("replica")) for seg in lineage
        ))
    audit = payload.get("audit") or {}
    mark = "conserved" if audit.get("conserved") else "NOT CONSERVED"
    print(f"  delivery: ingested={audit.get('ingested')} "
          f"emitted={audit.get('emitted')} [{mark}]")
    for e in audit.get("events") or []:
        print(f"   {e.get('code')}: {e.get('name')} "
              f"{e.get('description') or ''}")
    for a in payload.get("alerts") or []:
        print(f"   firing {a.get('severity') or 'warn'}: {a.get('name')}")
    return 1 if (audit.get("events") or payload.get("alerts")) else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m data_accelerator_tpu.obs",
        description="Observability tools over the JSONL flight recorder "
                    "and the /alerts surface.",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    tp = sub.add_parser(
        "trace", help="reconstruct one batch's span tree"
    )
    tp.add_argument("batch_id", help="batch time in epoch ms, or a trace id")
    tp.add_argument(
        "--file",
        default=os.environ.get("DATAX_TRACE_FILE", "telemetry.jsonl"),
        help="JSONL flight-recorder path (default: $DATAX_TRACE_FILE "
             "or ./telemetry.jsonl)",
    )
    tp.add_argument("--json", action="store_true", help="raw span JSON")
    tp.add_argument(
        "--stitch", action="store_true",
        help="group traces into replica lineage segments (the replica "
             "tag hosts stamp on batch spans); batch_id 'all' stitches "
             "every tagged trace in the recorder",
    )
    ap = sub.add_parser(
        "alerts", help="show a host's alert rules and firing set, or "
                       "validate a rules file"
    )
    ap.add_argument(
        "--url", default="http://127.0.0.1:8080",
        help="base URL of a host/website observability endpoint "
             "(GET <url>/alerts)",
    )
    ap.add_argument(
        "--validate", metavar="RULES_JSON",
        help="schema-check a rule file instead of querying a host",
    )
    ap.add_argument("--json", action="store_true", help="raw JSON payload")
    sp = sub.add_parser(
        "spans", help="span records from the flight recorder; "
                      "--aggregate renders the per-stage flame table"
    )
    sp.add_argument(
        "--file",
        default=os.environ.get("DATAX_TRACE_FILE", "telemetry.jsonl"),
        help="JSONL flight-recorder path (default: $DATAX_TRACE_FILE "
             "or ./telemetry.jsonl)",
    )
    sp.add_argument(
        "--aggregate", action="store_true",
        help="roll spans up per stage (count/total/p50/p99/max trace)",
    )
    sp.add_argument(
        "--limit", type=int, default=50,
        help="without --aggregate: how many recent spans to list",
    )
    sp.add_argument("--json", action="store_true", help="JSON rollup")
    pp = sub.add_parser(
        "profile", help="arm an on-demand jax profiler capture on a "
                        "live host (POST <url>/profile)"
    )
    pp.add_argument(
        "url", help="base URL of a host observability endpoint "
                    "(process.observability.port)",
    )
    pp.add_argument(
        "--seconds", type=float, default=5.0,
        help="capture window in seconds (default 5)",
    )
    pp.add_argument("--json", action="store_true", help="raw JSON payload")
    fp = sub.add_parser(
        "fleet", help="cross-replica telemetry rollup from the control "
                      "plane (GET <url>/fleet/metrics)"
    )
    fp.add_argument(
        "--url", default="http://127.0.0.1:5000",
        help="control-plane base URL (default http://127.0.0.1:5000)",
    )
    fp.add_argument(
        "--flow", help="drill into one flow "
                       "(GET <url>/fleet/flows/<flow>)",
    )
    fp.add_argument(
        "--output", help="audit this output's emitted counts instead "
                         "of the busiest one (with --flow)",
    )
    fp.add_argument("--json", action="store_true", help="raw JSON payload")
    args = parser.parse_args(argv)
    if args.cmd == "trace":
        return cmd_trace(args)
    if args.cmd == "alerts":
        return cmd_alerts(args)
    if args.cmd == "spans":
        return cmd_spans(args)
    if args.cmd == "profile":
        return cmd_profile(args)
    if args.cmd == "fleet":
        return cmd_fleet(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
