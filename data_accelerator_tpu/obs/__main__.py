"""Observability CLI.

``python -m data_accelerator_tpu.obs trace <batch_id> [--file F] [--json]``
reconstructs one micro-batch's span tree from the JSONL flight recorder
(the ``tracefile`` writer of obs/telemetry.py). ``<batch_id>`` is the
batch time in epoch ms (what ``streaming/batch/begin`` logs as
``batchTime``) or a raw trace id. Under cross-process propagation
(``datax.job.process.telemetry.parenttrace``) the rendered tree spans
the control-plane request down to the batch spans it caused.

Rotated segments (``<file>.N`` / ``<file>.N.gz`` — JsonlWriter
keep/compress rotation) are read oldest-first when present, so a batch
that rotated out mid-trace still reconstructs completely.

``python -m data_accelerator_tpu.obs alerts [--url U] [--json]``
fetches a host's (or the website's) ``GET /alerts`` and renders the
rule table with firing state; ``alerts --validate rules.json``
schema-checks a rule file (obs/alerts.py RULE_SCHEMA) and exits
non-zero on errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional


def _rotated_paths(path: str) -> List[str]:
    """Every on-disk segment of a rotated flight recorder, oldest
    first: ``<path>.N[.gz] .. <path>.1[.gz]`` then the active file
    (JsonlWriter keep/compress rotation)."""
    import glob as _glob

    rotated = []
    for p in _glob.glob(path + ".*"):
        suffix = p[len(path) + 1:]
        if suffix.endswith(".gz"):
            suffix = suffix[:-3]
        if suffix.isdigit():
            rotated.append((int(suffix), p))
    out = [p for _, p in sorted(rotated, reverse=True)]
    if os.path.exists(path):
        out.append(path)
    return out


def load_spans(path: str) -> List[dict]:
    import gzip

    spans: List[dict] = []
    for p in _rotated_paths(path):
        opener = gzip.open if p.endswith(".gz") else open
        with opener(p, "rt", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("type") == "span":
                    spans.append(rec)
    return spans


def find_traces(spans: List[dict], batch_id: str) -> List[str]:
    """Trace ids whose root span matches ``batch_id`` (batchTime or
    trace id). Batch roots carry ``batchTime``; under cross-process
    propagation they also carry a ``parent`` pointing into the
    control-plane trace, so the match keys on the property alone."""
    ids: List[str] = []
    for s in spans:
        if s.get("trace") == batch_id and s["trace"] not in ids:
            ids.append(s["trace"])
    for s in spans:
        bt = (s.get("properties") or {}).get("batchTime")
        if bt is not None and str(bt) == str(batch_id) \
                and s["trace"] not in ids:
            ids.append(s["trace"])
    return ids


def format_tree(spans: List[dict]) -> str:
    """Render one trace's spans as an indented tree ordered by start."""
    by_id: Dict[str, dict] = {s["span"]: s for s in spans}
    children: Dict[Optional[str], List[dict]] = {}
    for s in spans:
        parent = s.get("parent")
        if parent is not None and parent not in by_id:
            parent = None  # orphan (rotation cut its parent) -> top level
        children.setdefault(parent, []).append(s)
    for kids in children.values():
        kids.sort(key=lambda s: (s.get("startTs") or 0))

    lines: List[str] = []

    def emit(span: dict, prefix: str, is_last: bool, depth: int) -> None:
        props = span.get("properties") or {}
        extras = " ".join(
            f"{k}={v}" for k, v in sorted(props.items())
        )
        dur = span.get("durationMs")
        head = "" if depth == 0 else prefix + ("└─ " if is_last else "├─ ")
        lines.append(
            f"{head}{span.get('name')} "
            f"{dur:.2f} ms" + (f"  [{extras}]" if extras else "")
        )
        kids = children.get(span["span"], [])
        child_prefix = (
            "" if depth == 0 else prefix + ("   " if is_last else "│  ")
        )
        for i, k in enumerate(kids):
            emit(k, child_prefix, i == len(kids) - 1, depth + 1)

    roots = children.get(None, [])
    for i, r in enumerate(roots):
        emit(r, "", i == len(roots) - 1, 0)
    return "\n".join(lines)


def cmd_trace(args) -> int:
    spans = load_spans(args.file)
    if not spans:
        print(f"no spans found in {args.file}", file=sys.stderr)
        return 2
    trace_ids = find_traces(spans, args.batch_id)
    if not trace_ids:
        roots = sorted(
            {
                str((s.get("properties") or {}).get("batchTime"))
                for s in spans
                if (s.get("properties") or {}).get("batchTime") is not None
            }
        )
        print(
            f"no trace for batch {args.batch_id!r}; known batch ids: "
            f"{', '.join(roots[-10:]) or '(none)'}",
            file=sys.stderr,
        )
        return 1
    for tid in trace_ids:
        tspans = [s for s in spans if s.get("trace") == tid]
        if args.json:
            print(json.dumps(tspans, indent=1, default=str))
            continue
        print(f"trace {tid} ({len(tspans)} span(s))")
        print(format_tree(tspans))
    return 0


def cmd_alerts(args) -> int:
    from .alerts import validate_rules

    if args.validate:
        try:
            with open(args.validate, encoding="utf-8") as f:
                rules = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read rules file: {e}", file=sys.stderr)
            return 2
        errors = validate_rules(rules)
        if errors:
            for e in errors:
                print(e, file=sys.stderr)
            return 2
        print(f"{len(rules)} rule(s) valid")
        return 0
    import urllib.request

    url = args.url.rstrip("/") + "/alerts"
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            payload = json.loads(r.read() or b"{}")
    except OSError as e:
        print(f"cannot reach {url}: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(payload, indent=1, default=str))
        return 0
    firing = {a["name"] for a in payload.get("firing") or []}
    rules = payload.get("rules") or []
    flow = payload.get("flow") or ""
    print(f"alerts for {flow or '(unnamed)'} — "
          f"{len(firing)} firing / {len(rules)} rule(s)")
    for r in rules:
        state = r.get("state") or ("firing" if r["name"] in firing else "ok")
        mark = "!" if state == "firing" else (
            "~" if state == "pending" else " "
        )
        val = r.get("value")
        val_s = f"{val:.4g}" if isinstance(val, (int, float)) else "-"
        thr = r.get("threshold", r.get("burnRate"))
        print(f" {mark} {r['name']:<28} {state:<8} "
              f"value={val_s} threshold={thr} "
              f"severity={r.get('severity') or 'warn'}")
    return 1 if firing else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m data_accelerator_tpu.obs",
        description="Observability tools over the JSONL flight recorder "
                    "and the /alerts surface.",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    tp = sub.add_parser(
        "trace", help="reconstruct one batch's span tree"
    )
    tp.add_argument("batch_id", help="batch time in epoch ms, or a trace id")
    tp.add_argument(
        "--file",
        default=os.environ.get("DATAX_TRACE_FILE", "telemetry.jsonl"),
        help="JSONL flight-recorder path (default: $DATAX_TRACE_FILE "
             "or ./telemetry.jsonl)",
    )
    tp.add_argument("--json", action="store_true", help="raw span JSON")
    ap = sub.add_parser(
        "alerts", help="show a host's alert rules and firing set, or "
                       "validate a rules file"
    )
    ap.add_argument(
        "--url", default="http://127.0.0.1:8080",
        help="base URL of a host/website observability endpoint "
             "(GET <url>/alerts)",
    )
    ap.add_argument(
        "--validate", metavar="RULES_JSON",
        help="schema-check a rule file instead of querying a host",
    )
    ap.add_argument("--json", action="store_true", help="raw JSON payload")
    args = parser.parse_args(argv)
    if args.cmd == "trace":
        return cmd_trace(args)
    if args.cmd == "alerts":
        return cmd_alerts(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
