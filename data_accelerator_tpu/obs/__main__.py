"""Observability CLI.

``python -m data_accelerator_tpu.obs trace <batch_id> [--file F] [--json]``
reconstructs one micro-batch's span tree from the JSONL flight recorder
(the ``tracefile`` writer of obs/telemetry.py). ``<batch_id>`` is the
batch time in epoch ms (what ``streaming/batch/begin`` logs as
``batchTime``) or a raw trace id.

The rotated file (``<file>.1``) is read first when present, so a batch
that rotated out mid-trace still reconstructs completely.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional


def load_spans(path: str) -> List[dict]:
    spans: List[dict] = []
    for p in (path + ".1", path):
        if not os.path.exists(p):
            continue
        with open(p, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("type") == "span":
                    spans.append(rec)
    return spans


def find_traces(spans: List[dict], batch_id: str) -> List[str]:
    """Trace ids whose root span matches ``batch_id`` (batchTime or
    trace id)."""
    ids: List[str] = []
    for s in spans:
        if s.get("trace") == batch_id and s["trace"] not in ids:
            ids.append(s["trace"])
    for s in spans:
        if s.get("parent") is None:
            bt = (s.get("properties") or {}).get("batchTime")
            if bt is not None and str(bt) == str(batch_id) \
                    and s["trace"] not in ids:
                ids.append(s["trace"])
    return ids


def format_tree(spans: List[dict]) -> str:
    """Render one trace's spans as an indented tree ordered by start."""
    by_id: Dict[str, dict] = {s["span"]: s for s in spans}
    children: Dict[Optional[str], List[dict]] = {}
    for s in spans:
        parent = s.get("parent")
        if parent is not None and parent not in by_id:
            parent = None  # orphan (rotation cut its parent) -> top level
        children.setdefault(parent, []).append(s)
    for kids in children.values():
        kids.sort(key=lambda s: (s.get("startTs") or 0))

    lines: List[str] = []

    def emit(span: dict, prefix: str, is_last: bool, depth: int) -> None:
        props = span.get("properties") or {}
        extras = " ".join(
            f"{k}={v}" for k, v in sorted(props.items())
        )
        dur = span.get("durationMs")
        head = "" if depth == 0 else prefix + ("└─ " if is_last else "├─ ")
        lines.append(
            f"{head}{span.get('name')} "
            f"{dur:.2f} ms" + (f"  [{extras}]" if extras else "")
        )
        kids = children.get(span["span"], [])
        child_prefix = (
            "" if depth == 0 else prefix + ("   " if is_last else "│  ")
        )
        for i, k in enumerate(kids):
            emit(k, child_prefix, i == len(kids) - 1, depth + 1)

    roots = children.get(None, [])
    for i, r in enumerate(roots):
        emit(r, "", i == len(roots) - 1, 0)
    return "\n".join(lines)


def cmd_trace(args) -> int:
    spans = load_spans(args.file)
    if not spans:
        print(f"no spans found in {args.file}", file=sys.stderr)
        return 2
    trace_ids = find_traces(spans, args.batch_id)
    if not trace_ids:
        roots = sorted(
            {
                str((s.get("properties") or {}).get("batchTime"))
                for s in spans
                if s.get("parent") is None
                and (s.get("properties") or {}).get("batchTime") is not None
            }
        )
        print(
            f"no trace for batch {args.batch_id!r}; known batch ids: "
            f"{', '.join(roots[-10:]) or '(none)'}",
            file=sys.stderr,
        )
        return 1
    for tid in trace_ids:
        tspans = [s for s in spans if s.get("trace") == tid]
        if args.json:
            print(json.dumps(tspans, indent=1, default=str))
            continue
        print(f"trace {tid} ({len(tspans)} span(s))")
        print(format_tree(tspans))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m data_accelerator_tpu.obs",
        description="Observability tools over the JSONL flight recorder.",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    tp = sub.add_parser(
        "trace", help="reconstruct one batch's span tree"
    )
    tp.add_argument("batch_id", help="batch time in epoch ms, or a trace id")
    tp.add_argument(
        "--file",
        default=os.environ.get("DATAX_TRACE_FILE", "telemetry.jsonl"),
        help="JSONL flight-recorder path (default: $DATAX_TRACE_FILE "
             "or ./telemetry.jsonl)",
    )
    tp.add_argument("--json", action="store_true", help="raw span JSON")
    args = parser.parse_args(argv)
    if args.cmd == "trace":
        return cmd_trace(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
