"""File I/O layer: atomic writes, retries, gzip awareness, listing.

reference: datax-host fs/HadoopClient.scala:33-815 — the engine routes
*all* file access through one client that adds: gzip-aware reads (:201+),
atomic-ish writes via temp file + rename (:391-441), writes with timeout
and bounded retries (:333-362), and directory listing/copying. Here the
local filesystem (or any fuse/NFS mount of blob storage) stands in for
WASB/ADLS; the same single-module chokepoint keeps the semantics in one
place so a cloud-storage client can be swapped in behind these calls.
"""

from __future__ import annotations

import glob
import gzip
import itertools
import logging
import os
import shutil
import threading
import time
from typing import Iterable, List, Optional

logger = logging.getLogger(__name__)

_TMP_COUNTER = itertools.count()


def ensure_parent_dir(path: str) -> None:
    parent = os.path.dirname(os.path.abspath(path))
    if parent:
        os.makedirs(parent, exist_ok=True)


def is_gzip(path: str) -> bool:
    return path.endswith(".gz")


def read_text(path: str) -> str:
    """Gzip-aware whole-file text read (HadoopClient gzip read path).

    This is the fs chokepoint (reference: HadoopClient.scala resolves
    wasbs/abfs/local URIs in one place): ``objstore://`` URLs fetch from
    the shared object store, so any engine conf value may point at a
    file the control plane stored remotely."""
    from ..serve.objectstore import fetch_objstore_url, is_objstore_url

    if is_objstore_url(path):
        import os as _os

        return fetch_objstore_url(
            path, token=_os.environ.get("DATAX_OBJSTORE_TOKEN")
        )
    if is_gzip(path):
        with gzip.open(path, "rt", encoding="utf-8") as f:
            return f.read()
    with open(path, "r", encoding="utf-8") as f:
        return f.read()


def read_lines(path: str) -> List[str]:
    return read_text(path).splitlines()


def write_text(
    path: str,
    content: str,
    atomic: bool = True,
    abort: Optional[threading.Event] = None,
) -> None:
    """Write text, gzip-aware; atomic temp+rename by default
    (HadoopClient.scala:391-441 writeFile via temp + rename).

    The temp name is unique per call so concurrent writers (e.g. a
    timed-out attempt still running alongside its retry) never share a
    temp file. If ``abort`` is set before the final rename, the temp is
    discarded instead of installed — a superseded writer can't clobber
    a newer successful write.
    """
    ensure_parent_dir(path)
    target = (
        f"{path}.tmp.{os.getpid()}.{next(_TMP_COUNTER)}" if atomic else path
    )
    try:
        if is_gzip(path):
            with gzip.open(target, "wt", encoding="utf-8") as f:
                f.write(content)
        else:
            with open(target, "w", encoding="utf-8") as f:
                f.write(content)
        if atomic:
            if abort is not None and abort.is_set():
                raise InterruptedError(f"write of {path} superseded")
            os.replace(target, path)
    finally:
        if atomic and os.path.exists(target):
            try:
                os.remove(target)
            except OSError:
                pass


def write_with_timeout_and_retries(
    path: str,
    content: str,
    timeout_s: float = 10.0,
    retries: int = 3,
) -> bool:
    """Bounded-time write with retries (HadoopClient.scala:333-362:
    each attempt runs under a timeout; failures retry up to the limit).

    Returns True on success; raises the last error after exhausting
    retries (the caller's batch try/except owns the retry-batch policy).
    """
    last_err: Optional[BaseException] = None
    orphans: List[threading.Thread] = []
    for attempt in range(1, retries + 1):
        done = threading.Event()
        abort = threading.Event()
        err: List[BaseException] = []

        def attempt_write():
            try:
                write_text(path, content, abort=abort)
            except BaseException as e:  # noqa: BLE001 — captured for caller
                err.append(e)
            finally:
                done.set()

        t = threading.Thread(target=attempt_write, daemon=True)
        t.start()
        if not done.wait(timeout_s):
            # the orphan writes a unique temp and checks `abort` before
            # its rename. NOTE: an orphan that passes the check just
            # before abort.set() can still rename afterwards — the
            # window is narrowed, not closed. Within this call that is
            # harmless (every attempt writes identical bytes); writers
            # of *different* content to the same path must serialize
            # externally (the sink dispatcher does).
            abort.set()
            orphans.append(t)
            last_err = TimeoutError(
                f"write of {path} exceeded {timeout_s}s (attempt {attempt})"
            )
            logger.warning("%s", last_err)
            continue
        if err:
            last_err = err[0]
            logger.warning(
                "write of %s failed (attempt %d): %s", path, attempt, last_err
            )
            continue
        # best-effort: drain straggler attempts so none outlives success
        for o in orphans:
            o.join(timeout=0.1)
        return True
    assert last_err is not None
    raise last_err


def list_files(pattern_or_dir: str) -> List[str]:
    """List files by glob pattern or directory prefix, sorted."""
    if os.path.isdir(pattern_or_dir):
        out = []
        for root, _dirs, files in os.walk(pattern_or_dir):
            out.extend(os.path.join(root, f) for f in files)
        return sorted(out)
    return sorted(f for f in glob.glob(pattern_or_dir) if os.path.isfile(f))


def copy_file(src: str, dst: str) -> None:
    ensure_parent_dir(dst)
    shutil.copyfile(src, dst)


def delete_path(path: str) -> bool:
    """Remove a file or directory tree; True if anything was removed."""
    if os.path.isdir(path):
        shutil.rmtree(path, ignore_errors=True)
        return True
    if os.path.exists(path):
        os.remove(path)
        return True
    return False


def append_lines(path: str, lines: Iterable[str]) -> None:
    ensure_parent_dir(path)
    with open(path, "a", encoding="utf-8") as f:
        for line in lines:
            f.write(line.rstrip("\n") + "\n")


def file_modified_ms(path: str) -> int:
    return int(os.path.getmtime(path) * 1000)


def wait_for_file(path: str, timeout_s: float, poll_s: float = 0.05) -> bool:
    """Poll until a file exists (used by tests and job-handoff paths)."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(poll_s)
    return os.path.exists(path)
