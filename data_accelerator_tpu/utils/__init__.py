"""Shared utilities: data generation, gzip helpers, merging."""
