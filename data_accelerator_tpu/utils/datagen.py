"""Random event generation from an input schema.

Powers the local "one-box" simulated source and load generation for
benchmarks — the analog of the reference's schema-driven random JSON
generator (datax-utility DataGenerator.scala:18-160, consumed by
input/LocalStreamingSource.scala:19-41) and the SimulatedData service
(DataX.SimulatedData DataGen.cs:41-54).

Honored schema field metadata (same keys as the reference):
``allowedValues``, ``minValue``/``maxValue``, ``maxLength``,
``useCurrentTimeMillis``.
"""

from __future__ import annotations

import random
import string
import time
from typing import Dict, List, Optional

import numpy as np

from ..core.schema import ColType, Schema, StringDictionary

DEFAULT_MAX_LENGTH = 10


class DataGenerator:
    def __init__(self, schema: Schema, seed: Optional[int] = None):
        self.schema = schema
        self.rng = random.Random(seed)

    def random_row(self, now_ms: Optional[int] = None) -> dict:
        """One event as a nested dict matching the schema's dotted paths."""
        if now_ms is None:
            now_ms = int(time.time() * 1000)
        row: dict = {}
        for col in self.schema.columns:
            value = self._random_value(col.ctype, col.metadata, now_ms)
            _bury(row, col.name, value)
        return row

    def random_rows(self, n: int, now_ms: Optional[int] = None) -> List[dict]:
        return [self.random_row(now_ms) for _ in range(n)]

    def _random_value(self, ctype: ColType, md: dict, now_ms: int):
        rng = self.rng
        allowed = md.get("allowedValues")
        if ctype == ColType.STRING:
            if allowed:
                return str(rng.choice(allowed))
            max_len = int(md.get("maxLength", DEFAULT_MAX_LENGTH))
            return "".join(
                rng.choice(string.ascii_letters + string.digits)
                for _ in range(max_len)
            )
        if ctype == ColType.BOOLEAN:
            return rng.random() < 0.5
        if ctype == ColType.DOUBLE:
            if allowed:
                return float(rng.choice(allowed))
            lo = float(md.get("minValue", 0.0))
            hi = float(md.get("maxValue", 1.0))
            return rng.uniform(lo, hi)
        # LONG / TIMESTAMP: useCurrentTimeMillis wins, then allowedValues,
        # then min/max (reference: DataGenerator.scala long handling)
        if md.get("useCurrentTimeMillis") or ctype == ColType.TIMESTAMP:
            return now_ms
        if allowed:
            return int(rng.choice(allowed))
        lo = int(md.get("minValue", 0))
        hi = int(md.get("maxValue", 1000))
        return rng.randint(lo, max(lo, hi))

    # -- vectorized fast path (bench/ingest-rate testing) ---------------
    def random_columns(
        self,
        n: int,
        dictionary: StringDictionary,
        now_ms: Optional[int] = None,
        seed: Optional[int] = None,
    ) -> Dict[str, np.ndarray]:
        """Directly generate encoded column arrays (no per-row dicts) —
        the high-rate path for benchmarks, bypassing JSON entirely."""
        if now_ms is None:
            now_ms = int(time.time() * 1000)
        nprng = np.random.default_rng(seed)
        cols: Dict[str, np.ndarray] = {}
        for col in self.schema.columns:
            md = col.metadata
            allowed = md.get("allowedValues")
            if col.ctype == ColType.STRING:
                if allowed:
                    ids = np.array([dictionary.encode(str(v)) for v in allowed])
                    cols[col.name] = ids[nprng.integers(0, len(ids), n)].astype(
                        np.int32
                    )
                else:
                    cols[col.name] = np.full(
                        n, dictionary.encode("x"), dtype=np.int32
                    )
            elif col.ctype == ColType.TIMESTAMP or md.get("useCurrentTimeMillis"):
                cols[col.name] = np.zeros(n, dtype=np.int32)  # == base_ms
            elif col.ctype == ColType.BOOLEAN:
                cols[col.name] = nprng.integers(0, 2, n).astype(np.bool_)
            elif col.ctype == ColType.DOUBLE:
                if allowed:
                    vals = np.asarray(allowed, dtype=np.float32)
                    cols[col.name] = vals[nprng.integers(0, len(vals), n)]
                else:
                    lo = float(md.get("minValue", 0.0))
                    hi = float(md.get("maxValue", 1.0))
                    cols[col.name] = nprng.uniform(lo, hi, n).astype(np.float32)
            else:
                if allowed:
                    vals = np.asarray(allowed, dtype=np.int32)
                    cols[col.name] = vals[nprng.integers(0, len(vals), n)]
                else:
                    lo = int(md.get("minValue", 0))
                    hi = int(md.get("maxValue", 1000))
                    cols[col.name] = nprng.integers(lo, max(lo, hi) + 1, n).astype(
                        np.int32
                    )
        return cols


def _bury(obj: dict, dotted: str, value) -> None:
    parts = dotted.split(".")
    cur = obj
    for p in parts[:-1]:
        cur = cur.setdefault(p, {})
    cur[parts[-1]] = value
